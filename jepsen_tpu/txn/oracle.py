"""Host reference implementation of the transaction anomaly checker.

The executable semantic spec of :mod:`jepsen_tpu.txn` (the `lin/cpu.py`
role): Elle's list-append analysis (Kingsbury & Alvaro, VLDB 2020) in
Adya's formalization (*Weak Consistency*, MIT 1999) —

1. **Edge inference** (:func:`infer`): a history of transactions over
   list-append registers (micro-ops ``["append", k, v]`` /
   ``["r", k, observed-list]``) determines a per-key *version order*
   from the observed read prefixes (each appended value is unique per
   key, so every read traces its writers — Elle's recoverable-write
   rule), from which flow the dependency edges:

   - ``wr`` — T2 read the version T1 wrote (last element of the list).
   - ``ww`` — T2 appended the version immediately after T1's.
   - ``rw`` — T1 read a prefix and T2 appended the next version
     (anti-dependency; an empty read anti-depends on the key's first
     writer).
   - ``rt`` — realtime: T1 completed before T2 invoked (transitively
     reduced to the completion frontier; only built for
     strict-serializable checks).

   Indeterminate (``:info``) transactions follow the packed-history
   conventions of :mod:`jepsen_tpu.lin.prepare`: their appends count
   only when *observed* by some read (a write that may not have
   happened must not constrain the order); ``:fail`` appends are
   tracked solely to convict aborted reads (G1a).

2. **Cycle search** (:func:`tarjan`): strongly connected components,
   iteratively (100k-node histories blow the recursion limit).

3. **Classification** (:func:`classify`): each nontrivial SCC is
   explained by the strongest anomaly class its cycles witness —
   ``G0`` (write cycle: ww only), ``G1c`` (circular information flow:
   ww/wr with at least one wr), ``G-single`` (exactly one
   anti-dependency), ``G2-item`` (two or more) — with a canonical
   minimal witness cycle (:func:`witness_cycle`; BFS by ascending node
   id, so the device checker reproduces it bit-for-bit). Non-cycle
   anomalies from inference ride along: ``G1a`` (aborted read),
   ``garbage-read`` (a read observed a value no transaction ever
   appended — store corruption, not a dependency), ``duplicate-elements``,
   ``incompatible-order``.

:func:`check` is the public verdict entry point; the device engine
(:mod:`jepsen_tpu.txn.device`) must agree with it on verdict AND
witness (parity-fuzzed in tests/test_txn_device.py).
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any

import numpy as np

# Edge type ids, shared host<->device (pack.py edges, device.py masks).
WR, WW, RW, RT = 0, 1, 2, 3
EDGE_NAMES = {WR: "wr", WW: "ww", RW: "rw", RT: "rt"}

CYCLE_ANOMALIES = ("G0", "G1c", "G-single", "G2-item")
DIRECT_ANOMALIES = ("G1a", "garbage-read", "duplicate-elements",
                    "incompatible-order")

# Consistency model -> anomalies proscribed (Adya's hierarchy). SI
# admits G2-item (write skew lives there by design); serializability
# admits nothing cyclic; strict serializability additionally orders by
# realtime (rt edges join every cycle search).
CONSISTENCY_MODELS = {
    "serializable": CYCLE_ANOMALIES + DIRECT_ANOMALIES,
    "strict-serializable": CYCLE_ANOMALIES + DIRECT_ANOMALIES,
    "snapshot-isolation": ("G0", "G1c", "G-single") + DIRECT_ANOMALIES,
    "read-committed": ("G0", "G1c") + DIRECT_ANOMALIES,
}

MAX_WITNESSES = 8          # reported witnesses per anomaly type


@dataclass
class TxnNode:
    """One logical transaction (invocation + optional completion)."""

    idx: int                 # node id in the dependency graph
    op_index: int            # index of the invocation in the history
    process: Any
    mops: list               # micro-ops; completion's for ok, invoke's else
    ok: bool                 # True if completed ok; False if crashed (info)
    invoke_pos: int
    return_pos: int | None


@dataclass
class TxnGraph:
    """The inferred dependency graph + inference-level anomalies."""

    n: int
    src: np.ndarray          # i32[E]
    dst: np.ndarray          # i32[E]
    typ: np.ndarray          # i8[E]  (WR/WW/RW/RT)
    txns: list = field(default_factory=list)
    anomalies: dict = field(default_factory=dict)   # inference-level
    stats: dict = field(default_factory=dict)

    def edges_of(self, types: frozenset) -> tuple:
        m = np.isin(self.typ, list(types))
        return self.src[m], self.dst[m], self.typ[m]


class UnsupportedTxnHistory(Exception):
    """A history that is not list-append shaped (unknown micro-op f,
    non-unique appends are NOT this — those are anomalies)."""


def _mops_of(op) -> list:
    v = op.value
    if v is None:
        return []
    if not isinstance(v, (list, tuple)):
        raise UnsupportedTxnHistory(
            f"txn op value must be a micro-op list, got {type(v).__name__}")
    out = []
    for m in v:
        if not isinstance(m, (list, tuple)) or len(m) != 3 \
                or m[0] not in ("append", "r"):
            raise UnsupportedTxnHistory(f"bad micro-op {m!r}")
        out.append((m[0], m[1], m[2]))
    return out


def pair_txns(history) -> tuple[list[TxnNode], dict]:
    """Match txn invocations with completions. ``fail`` txns definitely
    did not commit — dropped from the graph, but their appends are kept
    (``failed_appends``: (k, v) -> op_index) so a read observing one is
    convicted as G1a. ``info`` txns may have committed: they stay, with
    the invocation's micro-ops (observed reads unknown)."""
    nodes: list[TxnNode] = []
    failed: dict = {}
    pending: dict[Any, tuple[int, Any]] = {}
    for pos, op in enumerate(history):
        if op.process == "nemesis" or op.f not in ("txn", "append-txn"):
            continue
        if op.is_invoke:
            pending[op.process] = (pos, op)
        elif op.process in pending:
            ipos, inv = pending.pop(op.process)
            if op.is_fail:
                for f, k, v in _mops_of(inv):
                    if f == "append":
                        failed[(k, v)] = inv.index if inv.index is not None \
                            else ipos
                continue
            ok = op.is_ok
            nodes.append(TxnNode(
                idx=len(nodes),
                op_index=inv.index if inv.index is not None else ipos,
                process=op.process,
                mops=_mops_of(op if ok else inv),
                ok=ok, invoke_pos=ipos,
                return_pos=pos if ok else None))
    for proc, (ipos, inv) in pending.items():   # dangling = crashed
        nodes.append(TxnNode(
            idx=len(nodes),
            op_index=inv.index if inv.index is not None else ipos,
            process=proc, mops=_mops_of(inv), ok=False,
            invoke_pos=ipos, return_pos=None))
    nodes.sort(key=lambda t: t.invoke_pos)
    for i, t in enumerate(nodes):
        t.idx = i
    return nodes, failed


def _realtime_edges(nodes: list[TxnNode]) -> list[tuple[int, int]]:
    """Transitively-reduced realtime order: each txn gets rt edges from
    the *frontier* of maximal completed txns at its invocation (a
    completed txn dominated by a later-invoked, earlier-completed one is
    dropped from the frontier — its edge is implied transitively)."""
    events = []   # (time, kind, node)  kind 0=return first at equal times
    for t in nodes:
        events.append((t.invoke_pos, 1, t))
        if t.return_pos is not None:
            events.append((t.return_pos, 0, t))
    events.sort(key=lambda e: (e[0], e[1]))
    frontier: list[TxnNode] = []
    edges = []
    for _pos, kind, t in events:
        if kind == 0:    # t completed: it dominates frontier members
            frontier[:] = [x for x in frontier
                           if x.return_pos >= t.invoke_pos]
            frontier.append(t)
        else:            # t invoked: edge from every frontier member
            for x in frontier:
                edges.append((x.idx, t.idx))
    return edges


def infer(history=None, nodes=None, failed=None,
          realtime: bool = False) -> TxnGraph:
    """Infer the wr/ww/rw(/rt) dependency graph from a list-append
    history (module docstring). Either a raw ``history`` or pre-paired
    ``(nodes, failed)`` may be supplied."""
    if nodes is None:
        nodes, failed = pair_txns(history)
    failed = failed or {}
    n = len(nodes)

    writer: dict = {}               # (k, v) -> node idx
    dupes: list = []
    appends_per_key: dict = defaultdict(int)
    for t in nodes:
        for f, k, v in t.mops:
            if f != "append":
                continue
            appends_per_key[k] += 1
            if (k, v) in writer and writer[(k, v)] != t.idx:
                dupes.append({"key": k, "value": v,
                              "txns": [writer[(k, v)], t.idx]})
            else:
                writer[(k, v)] = t.idx

    # Version order per key: the longest observed list; every other
    # read must be a prefix of it (list-append semantics).
    longest: dict = {}
    reads: list = []                # (node idx, k, observed tuple)
    for t in nodes:
        if not t.ok:
            continue                # info reads carry no observation
        for f, k, v in t.mops:
            if f != "r" or v is None:
                continue
            obs = tuple(v)
            reads.append((t.idx, k, obs))
            if len(obs) > len(longest.get(k, ())):
                longest[k] = obs

    incompatible: list = []
    g1a: list = []
    never: list = []
    for i, k, obs in reads:
        if obs != longest.get(k, ())[:len(obs)]:
            incompatible.append({"key": k, "txn": i, "observed": list(obs),
                                 "longest": list(longest.get(k, ()))})
        seen = set()
        for v in obs:
            if v in seen:
                dupes.append({"key": k, "value": v, "txns": [i],
                              "kind": "read-duplicate"})
            seen.add(v)
            if (k, v) not in writer:
                if (k, v) in failed:
                    g1a.append({"key": k, "value": v, "txn": i,
                                "failed-op-index": failed[(k, v)]})
                else:
                    never.append({"key": k, "value": v, "txn": i})

    es, ed, et = [], [], []

    def edge(a, b, ty):
        if a != b:
            es.append(a)
            ed.append(b)
            et.append(ty)

    # Unobserved COMMITTED appends: lists are append-only and a read
    # observes the whole list, so an ok append absent from the longest
    # read must order AFTER every observed version. It anchors a ww
    # tail edge from the last observed writer, and an rw
    # anti-dependency from every read that saw the full observed order
    # (the read provably missed it). Order among several unobserved
    # appends stays unknown — no edges between them. (:info appends
    # get neither: they may not have happened.)
    unobserved: dict = defaultdict(list)
    ok_txn = {t.idx for t in nodes if t.ok}
    observed_vals = {k: set(order) for k, order in longest.items()}
    for (k, v), w in writer.items():
        if w in ok_txn and v not in observed_vals.get(k, ()):
            unobserved[k].append(w)

    # ww: consecutive observed versions chain their writers.
    observed = 0
    for k, order in longest.items():
        prev = None
        for v in order:
            w = writer.get((k, v))
            if w is not None:
                observed += 1
                if prev is not None:
                    edge(prev, w, WW)
                prev = w
        if prev is not None:
            for w in unobserved.get(k, ()):
                edge(prev, w, WW)
    # wr / rw per read.
    for i, k, obs in reads:
        order = longest.get(k, ())
        if obs:
            w = writer.get((k, obs[-1]))
            if w is not None:
                edge(w, i, WR)
        if len(obs) < len(order):
            nxt = writer.get((k, order[len(obs)]))
            if nxt is not None:
                edge(i, nxt, RW)
        elif obs == order:
            for w in unobserved.get(k, ()):
                edge(i, w, RW)
    if realtime:
        for a, b in _realtime_edges(nodes):
            edge(a, b, RT)

    if es:
        e = np.unique(np.stack([np.asarray(es, np.int64),
                                np.asarray(ed, np.int64),
                                np.asarray(et, np.int64)], axis=1), axis=0)
        src, dst, typ = (e[:, 0].astype(np.int32),
                         e[:, 1].astype(np.int32),
                         e[:, 2].astype(np.int8))
    else:
        src = np.zeros(0, np.int32)
        dst = np.zeros(0, np.int32)
        typ = np.zeros(0, np.int8)

    anomalies = {}
    if g1a:
        anomalies["G1a"] = g1a[:MAX_WITNESSES]
    if never:
        # A value neither appended by any ok/info txn nor by a failed
        # one (which would be G1a): the store fabricated it. It maps to
        # no writer, so it forms no edges and no cycle — report it
        # directly or the corruption passes as valid.
        anomalies["garbage-read"] = never[:MAX_WITNESSES]
    if dupes:
        anomalies["duplicate-elements"] = dupes[:MAX_WITNESSES]
    if incompatible:
        anomalies["incompatible-order"] = incompatible[:MAX_WITNESSES]
    counts = {EDGE_NAMES[t]: int((typ == t).sum()) for t in (WR, WW, RW, RT)}
    stats = {"txns": n, "ok_txns": sum(1 for t in nodes if t.ok),
             "info_txns": sum(1 for t in nodes if not t.ok),
             "keys": len(appends_per_key), "reads": len(reads),
             "appends": sum(appends_per_key.values()),
             "observed_appends": observed,
             "edges": int(len(src)), "edge_counts": counts,
             "g1a": len(g1a), "garbage": len(never),
             "duplicates": len(dupes),
             "incompatible": len(incompatible)}
    return TxnGraph(n=n, src=src, dst=dst, typ=typ, txns=nodes,
                    anomalies=anomalies, stats=stats)


# --- SCC (iterative Tarjan) --------------------------------------------------


def _adjacency(n, src, dst) -> list[list[int]]:
    adj: list[list[int]] = [[] for _ in range(n)]
    order = np.lexsort((dst, src))
    for e in order:
        adj[int(src[e])].append(int(dst[e]))
    return adj


def tarjan(n: int, src, dst) -> list[list[int]]:
    """Nontrivial (size >= 2) SCCs, each sorted ascending, in ascending
    order of their minimum node — the canonical order classification
    and the device checker both use."""
    adj = _adjacency(n, src, dst)
    index = [-1] * n
    low = [0] * n
    on_stack = [False] * n
    stack: list[int] = []
    sccs: list[list[int]] = []
    counter = [0]

    for root in range(n):
        if index[root] != -1:
            continue
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack[v] = True
            recurse = False
            for i in range(pi, len(adj[v])):
                w = adj[v][i]
                if index[w] == -1:
                    work[-1] = (v, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if on_stack[w]:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
    sccs.sort(key=lambda c: c[0])
    return sccs


# --- classification ----------------------------------------------------------


def _scc_subgraph(scc: list[int], src, dst, typ, types: frozenset):
    in_scc = set(scc)
    adj: dict[int, list[tuple[int, int]]] = {v: [] for v in scc}
    for e in range(len(src)):
        a, b, t = int(src[e]), int(dst[e]), int(typ[e])
        if t in types and a in in_scc and b in in_scc:
            adj[a].append((b, t))
    for v in adj:
        adj[v].sort()
    return adj


def _bfs_path(adj, start: int, goal: int) -> list[tuple[int, int]] | None:
    """Shortest path start -> goal over ``adj`` (neighbors pre-sorted
    ascending, so the path is canonical); returns [(node, edge-type
    taken INTO node), ...] excluding start, or None."""
    from collections import deque

    parent: dict[int, tuple[int, int]] = {}
    q = deque([start])
    seen = {start}
    while q:
        v = q.popleft()
        for w, t in adj.get(v, ()):
            if w == goal:
                path = [(w, t)]
                x = v
                while x != start:
                    px, pt = parent[x]
                    path.append((x, pt))
                    x = px
                path.reverse()
                return path
            if w not in seen:
                seen.add(w)
                parent[w] = (v, t)
                q.append(w)
    return None


def witness_cycle(scc: list[int], src, dst, typ,
                  types: frozenset) -> dict | None:
    """Canonical minimal witness cycle through the SCC's smallest node:
    BFS (ascending neighbor order) from min(scc) back to itself within
    the SCC, restricted to ``types``. Deterministic, so oracle and
    device report byte-identical witnesses."""
    r = scc[0]
    adj = _scc_subgraph(scc, src, dst, typ, types)
    path = _bfs_path(adj, r, r)
    if path is None:
        return None
    nodes = [r] + [v for v, _t in path[:-1]]
    edges = [EDGE_NAMES[t] for _v, t in path]
    return {"nodes": nodes, "edges": edges,
            "rw-count": sum(1 for e in edges if e == "rw")}


def _has_internal_edge(scc: list[int], src, dst, typ, t: int) -> bool:
    """Does an edge of type ``t`` connect two nodes of this SCC?"""
    in_scc = set(scc)
    return any(int(typ[e]) == t and int(src[e]) in in_scc
               and int(dst[e]) in in_scc for e in range(len(src)))


def _closing_cycle(scc: list[int], src, dst, typ, edge_type: int,
                   path_types: frozenset) -> dict | None:
    """Canonical cycle through an ``edge_type`` edge of this SCC: for
    each such internal edge (u, v) ascending, the shortest
    ``path_types`` path v -> u closes it; the first closure wins. One
    search serves G1c's wr cycle (wr closed over ww/wr), G-single (rw
    closed over ww/wr — exactly one anti-dependency) and G2-item (rw
    closed over the full graph) so witness construction cannot drift
    between classes."""
    in_scc = set(scc)
    adj = _scc_subgraph(scc, src, dst, typ, path_types)
    pairs = sorted((int(src[e]), int(dst[e])) for e in range(len(src))
                   if int(typ[e]) == edge_type and int(src[e]) in in_scc
                   and int(dst[e]) in in_scc)
    for u, v in pairs:
        path = _bfs_path(adj, v, u)
        if path is not None:
            nodes = [u, v] + [w for w, _t in path[:-1]]
            edges = [EDGE_NAMES[edge_type]] + [EDGE_NAMES[t]
                                               for _w, t in path]
            return {"nodes": nodes, "edges": edges,
                    "rw-count": sum(1 for e in edges if e == "rw")}
    return None


def classify(graph: TxnGraph, requested, realtime: bool = False,
             sccs_by_tier: dict | None = None) -> dict:
    """Explain every nontrivial SCC with the strongest requested
    anomaly class its cycles witness (module docstring). ``sccs_by_tier``
    lets the device engine supply its own SCC decompositions per edge
    tier ({"ww": [...], "wwr": [...], "full": [...]}); absent tiers are
    computed here with :func:`tarjan`. Returns {anomaly: [witnesses]}."""
    requested = tuple(requested)
    src, dst, typ = graph.src, graph.dst, graph.typ
    rt_types = {RT} if realtime else set()
    sccs_by_tier = dict(sccs_by_tier or {})

    def tier_sccs(name, types):
        if name not in sccs_by_tier:
            m = np.isin(typ, list(types))
            sccs_by_tier[name] = tarjan(graph.n, src[m], dst[m])
        return sccs_by_tier[name]

    out: dict[str, list] = {}

    def add(kind, w):
        if w is not None and len(out.setdefault(kind, [])) < MAX_WITNESSES:
            out[kind].append(w)

    def saturated(kind):
        return len(out.get(kind, ())) >= MAX_WITNESSES

    ww_types = frozenset({WW} | rt_types)
    wwr_types = frozenset({WW, WR} | rt_types)
    full_types = frozenset({WW, WR, RW} | rt_types)

    # SCC node sets actually EXPLAINED under G0/G1c. The
    # strongest-explanation skip below is sound only for these: a
    # covering ww/wwr SCC whose class was not requested — or whose
    # cycles turned out ww-only under G1c — was never reported, so its
    # rw-bearing cycles must still be searched or a requested
    # G-single/G2-item (and the invalid verdict) would vanish.
    explained: set[tuple] = set()

    if "G0" in requested:
        for scc in tier_sccs("ww", ww_types):
            if saturated("G0"):
                # A ww-tier SCC is strongly connected via ww edges, so
                # its ww witness always exists — explained, just not
                # worth the O(E) search past the witness cap.
                explained.add(tuple(scc))
                continue
            w = witness_cycle(scc, src, dst, typ, ww_types)
            add("G0", w)
            if w is not None:
                explained.add(tuple(scc))
    if "G1c" in requested:
        for scc in tier_sccs("wwr", wwr_types):
            if saturated("G1c"):
                # Explained iff a wr edge cycles here (the witness
                # condition below) — an internal wr edge suffices, as
                # strong connectivity closes it.
                if _has_internal_edge(scc, src, dst, typ, WR):
                    explained.add(tuple(scc))
                continue
            w = witness_cycle(scc, src, dst, typ, wwr_types)
            # A ww-only minimal cycle in a wwr SCC is (possibly also)
            # a G0; it is G1c only when information flows — a wr edge
            # participates in some cycle of this SCC.
            if w is not None and "wr" not in w["edges"]:
                w = _closing_cycle(scc, src, dst, typ, WR, wwr_types)
            add("G1c", w)
            if w is not None:
                explained.add(tuple(scc))
    if "G-single" in requested or "G2-item" in requested:
        for scc in tier_sccs("full", full_types):
            if not (("G-single" in requested and not saturated("G-single"))
                    or ("G2-item" in requested
                        and not saturated("G2-item"))):
                break              # every requested rw class is capped
            # Strongest-explanation skip: an SCC whose node set is
            # exactly a ww/wwr SCC already reported under G0/G1c. A
            # bigger full-graph SCC may still add rw-bearing cycles,
            # so only skip exact matches.
            if tuple(scc) in explained:
                continue
            # A cycle with exactly ONE anti-dependency: the smallest
            # rw edge closed through ww/wr(/rt) only.
            single = _closing_cycle(scc, src, dst, typ, RW, wwr_types) \
                if "G-single" in requested else None
            if single is not None:
                add("G-single", single)
            elif "G2-item" in requested:
                # No single-rw cycle here, so any rw-closing cycle
                # carries >= 2 anti-dependencies (a 1-rw closure would
                # have been caught above) — the canonical G2 witness
                # closes the smallest rw edge through the full graph.
                add("G2-item", _closing_cycle(scc, src, dst, typ, RW,
                                              full_types))
    return out


def _witness_ops(graph: TxnGraph, anomalies: dict) -> None:
    """Attach op summaries to cycle witnesses in place (reporting)."""
    for kind, ws in anomalies.items():
        for w in ws:
            if isinstance(w, dict) and "nodes" in w and graph.txns:
                w["ops"] = [
                    {"index": graph.txns[i].op_index,
                     "process": graph.txns[i].process,
                     "ok": graph.txns[i].ok,
                     "mops": [list(m) for m in graph.txns[i].mops[:8]]}
                    for i in w["nodes"][:8]]


def resolve_anomalies(anomalies=None, consistency: str = "serializable",
                      realtime: bool | None = None):
    """(requested anomaly tuple, realtime flag) from checker options."""
    if anomalies is None:
        if consistency not in CONSISTENCY_MODELS:
            raise ValueError(
                f"unknown consistency model {consistency!r}; one of "
                f"{sorted(CONSISTENCY_MODELS)}")
        anomalies = CONSISTENCY_MODELS[consistency]
    if realtime is None:
        realtime = consistency == "strict-serializable"
    return tuple(anomalies), bool(realtime)


def check_graph(graph: TxnGraph, requested, realtime: bool = False,
                sccs_by_tier: dict | None = None) -> dict:
    """Verdict over an inferred graph: cycle classification + the
    inference-level direct anomalies, merged and filtered to the
    requested set."""
    found = classify(graph, requested, realtime=realtime,
                     sccs_by_tier=sccs_by_tier)
    for kind, ws in graph.anomalies.items():
        if kind in requested:
            found.setdefault(kind, ws)
    _witness_ops(graph, found)
    return {"valid?": not found,
            "analyzer": "txn-oracle",
            "anomaly-types": sorted(found),
            "anomalies": found,
            "stats": graph.stats}


def check(history, anomalies=None, consistency: str = "serializable",
          realtime: bool | None = None) -> dict:
    """Decide transactional consistency of a list-append history on the
    host — the semantic spec the device checker is parity-fuzzed
    against."""
    requested, rt = resolve_anomalies(anomalies, consistency, realtime)
    try:
        graph = infer(history, realtime=rt)
    except UnsupportedTxnHistory as e:
        return {"valid?": "unknown", "analyzer": "txn-oracle",
                "error": str(e)}
    out = check_graph(graph, requested, realtime=rt)
    out["consistency"] = consistency
    return out
