"""Transaction dependency-graph anomaly checking — Elle on device.

The transactional workload family for the SQL suites (cockroachdb,
tidb, galera, postgres-rds): histories of list-append transactions are
checked for snapshot-isolation / serializability violations by cycle
search over the inferred wr/ww/rw(/realtime) dependency graph
(Kingsbury & Alvaro, *Elle*, VLDB 2020; Adya, *Weak Consistency*, MIT
1999). See doc/txn.md.

- :mod:`jepsen_tpu.txn.oracle` — the executable CPU spec (the
  `lin/cpu.py` role): edge inference, Tarjan SCC, G0/G1c/G-single/
  G2-item classification with canonical minimal witness cycles.
- :mod:`jepsen_tpu.txn.pack`   — packed codec: sorted flat edge arrays
  edge lists (the `lin/prepare.py` role, same ``:info`` conventions).
- :mod:`jepsen_tpu.txn.device` — the device engine: trim + min-label
  SCC propagation inside a ``lax.while_loop`` (iteration ceiling
  in-program, supervised dispatches, quarantine-ledger recorded).
- :mod:`jepsen_tpu.txn.synth`  — history generators + seeded-anomaly
  corpora.

``checker.txn_cycles(...)`` is the suite-facing checker;
``make txn-smoke`` is the chip-free round-trip proof.
"""

from __future__ import annotations

from jepsen_tpu.txn import oracle as _oracle
from jepsen_tpu.txn.oracle import (CONSISTENCY_MODELS,  # noqa: F401
                                   CYCLE_ANOMALIES, DIRECT_ANOMALIES,
                                   TxnGraph, UnsupportedTxnHistory)


def check(history, anomalies=None, consistency: str = "serializable",
          realtime: bool | None = None, algorithm: str = "tpu") -> dict:
    """Decide transactional consistency of a list-append history.

    ``algorithm``: ``"tpu"`` packs the dependency graph and runs the
    device SCC engine (:mod:`jepsen_tpu.txn.device`; falls back tier
    by tier to the host on faults/wedges/quarantine); ``"cpu"`` runs
    the oracle end to end. Both classify with the same shared code and
    report identical verdicts + witness cycles (parity-fuzzed).
    """
    if algorithm == "cpu":
        return _oracle.check(history, anomalies=anomalies,
                             consistency=consistency, realtime=realtime)
    if algorithm != "tpu":
        raise ValueError(f"unknown txn algorithm {algorithm!r}")
    from jepsen_tpu.txn import device, pack

    requested, rt = _oracle.resolve_anomalies(anomalies, consistency,
                                              realtime)
    try:
        pt = pack.pack(history, realtime=rt)
    except UnsupportedTxnHistory as e:
        return {"valid?": "unknown", "analyzer": "txn-pack",
                "error": str(e)}
    return device.check_packed(pt, anomalies=requested,
                               consistency=consistency, realtime=rt)
