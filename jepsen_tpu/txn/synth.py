"""Synthetic list-append histories for the txn checker.

The :mod:`jepsen_tpu.lin.synth` role for the transactional family:

- :func:`generate_list_append_history` — a serializable-by-construction
  concurrent history (every transaction applies atomically at a
  linearization point inside its invocation window), optionally with
  crashed (``:info``) transactions, at any op count — the ``txn_c30``
  bench shape and the 100k-op acceptance history.
- :func:`seeded_anomaly_history` — minimal hand-built histories with a
  KNOWN anomaly (G0 / G1c / G-single / G2-item / G1a), used by the
  parity tests and the smoke: the checker must find and classify each
  identically on oracle and device.
- :func:`splice_anomaly` — injects a seeded anomaly pattern (on fresh
  keys) into a big healthy history, so 100k-op invalid corpora exist.
"""

from __future__ import annotations

import random

from jepsen_tpu.history import Op


def _invoke(process, mops):
    return Op("invoke", "txn", [list(m) for m in mops], process)


def _complete(process, mops, typ="ok"):
    return Op(typ, "txn", [list(m) for m in mops], process)


def generate_list_append_history(n_txns: int, concurrency: int = 10,
                                 keys: int = 8, seed: int = 0,
                                 mops_per_txn: tuple = (1, 4),
                                 read_frac: float = 0.5,
                                 crash_prob: float = 0.0,
                                 max_crashes: int = 16) -> list[Op]:
    """Serializable concurrent history: a shared store applies each
    txn atomically in invocation order; up to ``concurrency`` txns are
    in flight, and completions are emitted after a random number of
    other invocations (so the realtime order is a genuine partial
    order). Crashed txns apply their appends (recoverable iff observed
    later) but never complete."""
    rng = random.Random(seed)
    store: dict = {k: [] for k in range(keys)}
    next_val = [0]
    next_proc = [concurrency]
    history: list[Op] = []
    inflight: list = []   # (process, completion op, remaining delay)
    crashes = 0
    free_procs = list(range(concurrency))

    def drain(force: bool = False):
        nonlocal inflight
        keep = []
        for proc, comp, delay in inflight:
            if force or delay <= 0:
                history.append(comp)
                free_procs.append(proc)
            else:
                keep.append((proc, comp, delay - 1))
        inflight = keep

    for _ in range(n_txns):
        while not free_procs:
            drain()
            if not free_procs and inflight:
                proc, comp, _d = inflight.pop(0)
                history.append(comp)
                free_procs.append(proc)
        proc = free_procs.pop(rng.randrange(len(free_procs)))
        n_mops = rng.randint(*mops_per_txn)
        mops = []
        for _m in range(n_mops):
            k = rng.randrange(keys)
            if rng.random() < read_frac:
                mops.append(("r", k, None))
            else:
                next_val[0] += 1
                mops.append(("append", k, next_val[0]))
        history.append(_invoke(proc, mops))
        # Atomic apply at invocation (a valid linearization point).
        done = []
        for f, k, v in mops:
            if f == "append":
                store[k].append(v)
                done.append(("append", k, v))
            else:
                done.append(("r", k, list(store[k])))
        if crashes < max_crashes and rng.random() < crash_prob:
            crashes += 1
            # Crashed: appends applied, observation lost, no return.
            # The process id is dead (a reused id would alias the
            # dangling invoke in pairing); a fresh one replaces it.
            free_procs.append(next_proc[0])
            next_proc[0] += 1
            continue
        inflight.append((proc, _complete(proc, done),
                         rng.randrange(0, concurrency)))
    drain(force=True)
    return history


# --- seeded anomalies --------------------------------------------------------

def _txn(history, proc, mops_inv, mops_ok=None, typ="ok"):
    history.append(_invoke(proc, mops_inv))
    if typ == "fail":
        history.append(_complete(proc, mops_inv, "fail"))
    elif typ == "ok":
        history.append(_complete(proc, mops_ok or mops_inv, "ok"))
    # typ "info": no completion (dangling invoke = crashed)


def seeded_anomaly_history(kind: str, key_base=None) -> list[Op]:
    """A minimal history exhibiting exactly ``kind``. Keys take the
    form ``f"{key_base}:x"`` so patterns splice into healthy histories
    without touching their keys."""
    kb = key_base if key_base is not None else "seed"
    x, y = f"{kb}:x", f"{kb}:y"
    h: list[Op] = []
    if kind == "G0":
        # ww(x): T0 -> T1 but ww(y): T1 -> T0 (observed interleaving).
        h.append(_invoke(0, [["append", x, 1], ["append", y, 2]]))
        h.append(_invoke(1, [["append", x, 3], ["append", y, 4]]))
        h.append(_complete(0, [["append", x, 1], ["append", y, 2]]))
        h.append(_complete(1, [["append", x, 3], ["append", y, 4]]))
        _txn(h, 2, [["r", x, None], ["r", y, None]],
             [["r", x, [1, 3]], ["r", y, [4, 2]]])
    elif kind == "G1c":
        # wr(x): T0 -> T1 and wr(y): T1 -> T0.
        h.append(_invoke(0, [["append", x, 1], ["r", y, None]]))
        h.append(_invoke(1, [["append", y, 2], ["r", x, None]]))
        h.append(_complete(0, [["append", x, 1], ["r", y, [2]]]))
        h.append(_complete(1, [["append", y, 2], ["r", x, [1]]]))
    elif kind == "G-single":
        # T0 reads y's version from T1 (wr T1->T0) but misses T1's
        # append to x (rw T0->T1): read skew, one anti-dependency.
        h.append(_invoke(1, [["append", y, 5], ["append", x, 7]]))
        h.append(_complete(1, [["append", y, 5], ["append", x, 7]]))
        h.append(_invoke(0, [["r", y, None], ["r", x, None]]))
        h.append(_complete(0, [["r", y, [5]], ["r", x, []]]))
        # A later read establishes x's version order.
        _txn(h, 2, [["r", x, None]], [["r", x, [7]]])
    elif kind == "G2-item":
        # Write skew: each reads the other's key before its append.
        h.append(_invoke(0, [["r", x, None], ["append", y, 1]]))
        h.append(_invoke(1, [["r", y, None], ["append", x, 2]]))
        h.append(_complete(0, [["r", x, []], ["append", y, 1]]))
        h.append(_complete(1, [["r", y, []], ["append", x, 2]]))
        _txn(h, 2, [["r", x, None], ["r", y, None]],
             [["r", x, [2]], ["r", y, [1]]])
    elif kind == "G1a":
        # Aborted read: T1 observes a value whose append failed.
        _txn(h, 0, [["append", x, 9]], typ="fail")
        _txn(h, 1, [["r", x, None]], [["r", x, [9]]])
    else:
        raise ValueError(f"unknown seeded anomaly {kind!r}")
    return h


def splice_anomaly(history: list[Op], kind: str, seed: int = 0,
                   n: int = 1) -> list[Op]:
    """Inject ``n`` seeded ``kind`` patterns (fresh keys, fresh process
    ids) at random positions of a healthy history."""
    rng = random.Random(seed)
    out = list(history)
    procs = {op.process for op in history
             if isinstance(op.process, int)}
    base_proc = (max(procs) + 1) if procs else 0
    for i in range(n):
        # Key base carries kind+seed: two splices into the same history
        # must never share keys (colliding patterns read each other's
        # appends and manufacture incompatible-order noise).
        pat = seeded_anomaly_history(kind, key_base=f"{kind}{seed}.{i}")
        pat = [op.replace(process=base_proc + 10 * i + op.process)
               for op in pat]
        pos = rng.randrange(len(out) + 1)
        out[pos:pos] = pat
    return out
