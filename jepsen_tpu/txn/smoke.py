"""`make txn-smoke`: generate -> pack -> check -> classify, chip-free.

The serve-smoke habit for the txn subsystem: a FRESH-process proof on
the forced 8-device CPU mesh that the whole txn path round-trips —
a healthy concurrent list-append history decides valid on device, and
every seeded anomaly corpus (G0 / G1c / G-single / G2-item / G1a) is
found AND classified identically by the device engine and the CPU
oracle, witness cycles included. Prints one JSON result line and exits
0/1 — timeout-guarded by the Makefile so a wedge cannot hold the
shell.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> int:
    t_start = time.time()
    # CPU mesh BEFORE any jax backend init (CLAUDE.md: the TPU plugin
    # force-selects its platform; the smoke must never take the chip).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from jepsen_tpu.util import enable_compile_cache

    enable_compile_cache()

    from jepsen_tpu import txn
    from jepsen_tpu.txn import oracle, synth

    out: dict = {"checks": []}
    ok = True

    # 1. Healthy concurrent history: valid on device, parity with cpu.
    h = synth.generate_list_append_history(
        800, concurrency=10, keys=8, seed=7, crash_prob=0.01,
        max_crashes=6)
    t0 = time.time()
    dev = txn.check(h, consistency="serializable", algorithm="tpu")
    cpu = txn.check(h, consistency="serializable", algorithm="cpu")
    rec = {"case": "healthy", "ops": len(h),
           "edges": (dev.get("device-stats") or {}).get("edges"),
           "device": dev.get("valid?"), "cpu": cpu.get("valid?"),
           "seconds": round(time.time() - t0, 2)}
    good = dev.get("valid?") is True and cpu.get("valid?") is True \
        and not dev.get("fallbacks")
    rec["ok"] = good
    ok = ok and good
    out["checks"].append(rec)

    # 2. Seeded anomalies: found + classified identically, witnesses
    # included (the acceptance contract of ISSUE 9).
    for kind in ("G0", "G1c", "G-single", "G2-item", "G1a"):
        h = synth.seeded_anomaly_history(kind)
        dev = txn.check(h, consistency="serializable", algorithm="tpu")
        cpu = txn.check(h, consistency="serializable", algorithm="cpu")
        good = (dev.get("valid?") is False
                and kind in dev.get("anomaly-types", [])
                and dev.get("anomaly-types") == cpu.get("anomaly-types")
                and dev.get("anomalies") == cpu.get("anomalies"))
        out["checks"].append({"case": kind,
                              "device": dev.get("anomaly-types"),
                              "cpu": cpu.get("anomaly-types"),
                              "ok": good})
        ok = ok and good

    # 3. A spliced anomaly inside a bigger healthy history.
    h = synth.splice_anomaly(
        synth.generate_list_append_history(400, concurrency=8, seed=3),
        "G2-item", seed=3)
    dev = txn.check(h, consistency="serializable", algorithm="tpu")
    si = txn.check(h, consistency="snapshot-isolation", algorithm="tpu")
    good = dev.get("valid?") is False \
        and "G2-item" in dev.get("anomaly-types", []) \
        and si.get("valid?") is True   # SI admits pure write skew
    out["checks"].append({"case": "spliced-G2",
                          "serializable": dev.get("anomaly-types"),
                          "snapshot-isolation": si.get("valid?"),
                          "ok": good})
    ok = ok and good

    out["graph_stats"] = oracle.infer(
        synth.generate_list_append_history(200, seed=1)).stats

    # 4. Pack meter (ISSUE 16): every check above went through the
    # version-order join, so the pack counters must have accumulated,
    # and the pack sub-dict must survive the ledger record -> load
    # round trip (the schema bench's _probe_main forwards) without
    # tripping any gate rule — it is observability, not evidence.
    from jepsen_tpu.obs import ledger as perf_ledger
    from jepsen_tpu.txn import pack as txn_pack

    ps = txn_pack.pack_stats()
    pack = {"pack_s": round(ps["pack_s"], 3),
            "pack_calls": ps["pack_calls"]}
    good = ps["pack_calls"] > 0 and ps["pack_s"] >= 0
    out["checks"].append({"case": "pack-meter", "pack": pack,
                          "ok": good})
    ok = ok and good
    out["ok"] = ok
    # Cross-run perf ledger (doc/observability.md § Perf ledger):
    # record() never raises — a ledger failure cannot cost the smoke.
    rec = perf_ledger.record("txn-smoke", kind="smoke",
                             wall_s=time.time() - t_start, verdict=ok,
                             extra={"pack": pack})
    if rec is not None:
        loaded = [r for r in perf_ledger.load()
                  if r.get("probe") == "txn-smoke" and "pack" in r]
        roundtrip = bool(loaded) and loaded[-1]["pack"] == pack \
            and not [f for f in perf_ledger.gate(perf_ledger.load())
                     if f["probe"] == "txn-smoke"
                     and f["rule"] != "wall-regression"]
        out["checks"].append({"case": "pack-roundtrip",
                              "ok": roundtrip})
        if not roundtrip:
            out["ok"] = ok = False
            print(json.dumps(out, default=str))
            return 1
    print(json.dumps(out, default=str))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
