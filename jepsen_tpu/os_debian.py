"""Debian OS provisioning.

Re-design of `jepsen/src/jepsen/os/debian.clj` (167 LoC): apt package
management with idempotent install (:77-95), repo management (:103-117),
JDK install (:119-137), hostfile normalization and base packages in the OS
setup (:139-167).
"""

from __future__ import annotations

from jepsen_tpu import control as c
from jepsen_tpu import os_ as os_ns

BASE_PACKAGES = ["wget", "curl", "vim", "man-db", "faketime", "ntpdate",
                 "unzip", "iptables", "psmisc", "tar", "bzip2",
                 "iputils-ping", "iproute2", "rsyslog", "logrotate"]


def installed(packages) -> set:
    """Which of the given packages are installed? (debian.clj:38-48)"""
    out = c.exec_("dpkg", "--get-selections", may_fail=True)
    have = {line.split()[0].split(":")[0]
            for line in out.splitlines()
            if line.strip().endswith("install")}
    return {p for p in packages if p in have}


def uninstall(packages) -> None:
    """Remove packages (debian.clj:56-64)."""
    packages = list(packages)
    if packages:
        with c.su():
            c.exec_("apt-get", "remove", "--purge", "-y", *packages)


def update() -> None:
    """apt-get update (debian.clj:66-69)."""
    with c.su():
        c.exec_("apt-get", "update")


def upgrade() -> None:
    """apt-get upgrade (debian.clj:71-75)."""
    with c.su():
        c.exec_("apt-get", "upgrade", "-y")


def install(packages, force: bool = False) -> None:
    """Install missing packages, idempotently (debian.clj:77-95)."""
    packages = list(packages)
    have = set() if force else installed(packages)
    missing = [p for p in packages if p not in have]
    if missing:
        with c.su():
            c.exec_("env", "DEBIAN_FRONTEND=noninteractive",
                    "apt-get", "install", "-y", *missing)


def add_repo(name: str, line: str, keyserver: str | None = None,
             key: str | None = None) -> None:
    """Add an apt repo + optional key (debian.clj:103-117)."""
    with c.su():
        c.exec_("tee", f"/etc/apt/sources.list.d/{name}.list", stdin=line)
        if keyserver and key:
            c.exec_("apt-key", "adv", "--keyserver", keyserver,
                    "--recv", key)
        c.exec_("apt-get", "update")


def install_jdk(version: str = "17") -> None:
    """Install a JDK (the reference pins jdk8 via backports,
    debian.clj:119-137; modern debians carry openjdk directly)."""
    install([f"openjdk-{version}-jdk-headless"])


def setup_hostfile(test, node) -> None:
    """Make the node refer to itself by its test name (debian.clj:145-155
    equivalent): hostname + /etc/hosts entry."""
    with c.su():
        c.exec_("hostname", node, may_fail=True)
        hosts = ["127.0.0.1 localhost", f"127.0.1.1 {node}"]
        c.exec_("tee", "/etc/hosts", stdin="\n".join(hosts) + "\n")


class DebianOS(os_ns.OS):
    """Debian setup: hostfile, apt update, base packages
    (debian.clj:139-167)."""

    def setup(self, test, node):
        setup_hostfile(test, node)
        install(BASE_PACKAGES)

    def teardown(self, test, node):
        pass


os = DebianOS()
