"""Command-line runner.

Re-design of `jepsen/src/jepsen/cli.clj` (334 LoC): a subcommand
dispatcher with the standard test option set (cli.clj:52-87 — --node,
--nodes-file, --username, --password, --concurrency with the "3n"
multiplier :123-138, --time-limit, --test-count, --ssh-private-key), the
exit-code contract (cli.clj:103-112: 0 = valid, 1 = invalid, 2 = unknown,
254 = error, 255 = usage), `single_test_cmd` for suites (:295-329) and
`serve_cmd` for the results web server (:278-293).

A suite module plugs in exactly like the reference's `-main`s::

    from jepsen_tpu import cli
    cli.run(cli.single_test_cmd(my_test_fn, opt_spec=[...]), argv)
"""

from __future__ import annotations

import argparse
import logging
import sys
import traceback
from typing import Callable

from jepsen_tpu import checker as checker_ns

EXIT_OK = 0
EXIT_INVALID = 1
EXIT_UNKNOWN = 2
EXIT_ERROR = 254
EXIT_USAGE = 255

# --- declarative command registry -------------------------------------------
# The standard (non-suite-specific) subcommands as one table instead of
# call-site lists that drifted per entry point: each entry is a no-arg
# factory returning a command spec {name, parser, run, help,
# description?}. ``standard_commands()`` instantiates them in
# registration order; ``run()`` wires ``description`` into the
# subparser so every command's ``--help`` explains itself.
# ``single_test_cmd`` stays a parameterized factory (it needs the
# suite's test_fn) and composes with the registry via
# ``suite_commands``.

_REGISTRY: dict[str, Callable[[], dict]] = {}


def command(factory: Callable[[], dict]) -> Callable[[], dict]:
    """Register a standard-command factory (decorator). The command
    name comes from the spec the factory builds, so the table cannot
    disagree with the parser."""
    _REGISTRY[factory()["name"]] = factory
    return factory


def standard_commands(names=None) -> list[dict]:
    """Instantiate the registered standard commands (all, or ``names``
    in registry order) — what every suite ``-main`` and the bare
    ``jepsen-tpu`` entry point share."""
    return [f() for n, f in _REGISTRY.items()
            if names is None or n in names]


def add_test_opts(p: argparse.ArgumentParser) -> None:
    """The standard test option set (cli.clj:52-87)."""
    p.add_argument("--node", action="append", dest="nodes", metavar="NODE",
                   help="node to test; repeatable (default n1..n5)")
    p.add_argument("--nodes-file", help="file with one node per line")
    p.add_argument("--username", default="root", help="ssh username")
    p.add_argument("--password", help="ssh password")
    p.add_argument("--ssh-private-key", dest="private_key_path",
                   help="path to an SSH identity file")
    p.add_argument("--strict-host-key-checking", action="store_true",
                   help="verify host keys")
    p.add_argument("--concurrency", default="1n",
                   help='number of workers, e.g. "10" or "3n" '
                        "(3 x node count)")
    p.add_argument("--time-limit", type=float, default=60.0,
                   help="how long to run the workload, seconds")
    p.add_argument("--test-count", type=int, default=1,
                   help="how many times to run the test")
    p.add_argument("--transport", default="ssh",
                   choices=["ssh", "local", "dummy"],
                   help="control-plane transport")
    p.add_argument("--store", default="store", help="results directory")


def parse_concurrency(spec: str, n_nodes: int) -> int:
    """'10' -> 10 workers; '3n' -> 3 x node count (cli.clj:123-138)."""
    spec = str(spec).strip()
    try:
        if spec.endswith("n"):
            return int(spec[:-1] or 1) * n_nodes
        return int(spec)
    except ValueError:
        raise UsageError(
            f"--concurrency must be an integer optionally followed by 'n', "
            f"got {spec!r}")


class UsageError(Exception):
    pass


def options_to_test(opts: argparse.Namespace) -> dict:
    """Build the base test map from parsed options (the reference's
    test-opt-fn pipeline, cli.clj:156-197)."""
    nodes = opts.nodes
    if opts.nodes_file:
        with open(opts.nodes_file) as fh:
            nodes = [line.strip() for line in fh if line.strip()]
    if not nodes:
        nodes = ["n1", "n2", "n3", "n4", "n5"]
    ssh = {"username": opts.username,
           "password": opts.password,
           "private-key-path": opts.private_key_path,
           "strict-host-key-checking": opts.strict_host_key_checking}
    return {"nodes": nodes,
            "ssh": ssh,
            "transport": opts.transport,
            "concurrency": parse_concurrency(opts.concurrency, len(nodes)),
            "time-limit": opts.time_limit,
            "store-base": opts.store}


def single_test_cmd(test_fn: Callable[[dict], dict],
                    name: str = "test",
                    opt_spec: Callable[[argparse.ArgumentParser], None]
                    | None = None) -> dict:
    """A subcommand spec running `test_fn(options)` through the core runner
    --test-count times (cli.clj:295-329)."""

    def build_parser(p: argparse.ArgumentParser):
        add_test_opts(p)
        if opt_spec:
            opt_spec(p)

    def run_cmd(opts: argparse.Namespace) -> int:
        from jepsen_tpu import core

        # invalid (definite violation) dominates unknown dominates ok —
        # same priority order as merge_valid, not numeric exit-code order.
        severity = {EXIT_OK: 0, EXIT_UNKNOWN: 1, EXIT_INVALID: 2}
        worst = EXIT_OK
        for _ in range(opts.test_count):
            test = test_fn({**vars(opts), **options_to_test(opts)})
            result = core.run(test)
            valid = result.get("results", {}).get(checker_ns.VALID)
            code = (EXIT_OK if valid is True else
                    EXIT_INVALID if valid is False else EXIT_UNKNOWN)
            if severity[code] > severity[worst]:
                worst = code
        return worst

    return {"name": name, "parser": build_parser, "run": run_cmd,
            "help": f"run the {name} test"}


def suite_commands(test_fn: Callable[[dict], dict],
                   opt_spec: Callable[[argparse.ArgumentParser], None]
                   | None = None) -> list[dict]:
    """The standard command set of a suite's -main: run the test, serve
    results, re-analyze saved histories (etcd.clj:182-188 composes
    single-test-cmd + serve-cmd the same way)."""

    def spec(p: argparse.ArgumentParser):
        p.add_argument("--fake", action="store_true",
                       help="run against the in-memory workload fake "
                            "(no cluster; dummy control transport)")
        if opt_spec:
            opt_spec(p)

    return [single_test_cmd(test_fn, opt_spec=spec)] \
        + standard_commands()


@command
def serve_cmd() -> dict:
    """Run the results web server (cli.clj:278-293). NOT the checker
    daemon — that is ``serve-checker`` (two different sockets, two
    different jobs; the names say which)."""

    def build_parser(p: argparse.ArgumentParser):
        p.add_argument("--port", "-p", type=int, default=8080)
        p.add_argument("--host", "-b", default="0.0.0.0")
        p.add_argument("--store", default="store")

    def run_cmd(opts: argparse.Namespace) -> int:
        from jepsen_tpu import web

        web.serve(host=opts.host, port=opts.port, base=opts.store)
        return EXIT_OK

    return {"name": "serve", "parser": build_parser, "run": run_cmd,
            "help": "serve the results web browser (NOT the checker "
                    "daemon: see serve-checker)",
            "description":
                "HTTP browser over the store/ results directory "
                "(runs table, file previews, zip downloads). The "
                "linearizability checker daemon is the separate "
                "`serve-checker` command."}


@command
def serve_checker_cmd() -> dict:
    """Run the checker daemon (jepsen_tpu.service): the persistent
    shape-binned batch checker amortizing the warm chip across queued
    histories."""

    def build_parser(p: argparse.ArgumentParser):
        p.add_argument("--port", "-p", type=int, default=None,
                       help="listen port (default: "
                            "JEPSEN_TPU_SERVICE_PORT or 8642; 0 = "
                            "ephemeral)")
        p.add_argument("--host", "-b", default="127.0.0.1")
        p.add_argument("--queue-bound", type=int, default=None,
                       help="admission queue bound (backpressure past "
                            "it); default JEPSEN_TPU_SERVICE_QUEUE")
        p.add_argument("--flush-ms", type=float, default=None,
                       help="bin max-wait before a partial batch "
                            "flushes; default JEPSEN_TPU_SERVICE_"
                            "FLUSH_MS")
        p.add_argument("--max-batch", type=int, default=None,
                       help="histories per vmapped device program; "
                            "default JEPSEN_TPU_SERVICE_MAX_BATCH")
        p.add_argument("--deadline", type=float, default=None,
                       help="per-request decide deadline, seconds; "
                            "default JEPSEN_TPU_SERVICE_DEADLINE_S")
        p.add_argument("--stats-file", default=None,
                       help="stats snapshot path (web.py /service "
                            "page); default JEPSEN_TPU_SERVICE_STATS")
        p.add_argument("--workers", type=int, default=None,
                       help="decide worker pool size; default "
                            "JEPSEN_TPU_SERVICE_WORKERS (1)")
        p.add_argument("--journal", default=None,
                       help="durable request journal path (restart "
                            "replays unsettled entries); default "
                            "JEPSEN_TPU_SERVICE_JOURNAL (off)")

    def run_cmd(opts: argparse.Namespace) -> int:
        from jepsen_tpu.service.daemon import serve_checker

        serve_checker(host=opts.host, port=opts.port,
                      bound=opts.queue_bound,
                      flush_ms_=opts.flush_ms,
                      max_batch_=opts.max_batch,
                      deadline_s=opts.deadline,
                      stats_file=opts.stats_file,
                      workers=opts.workers,
                      journal=opts.journal)
        return EXIT_OK

    return {"name": "serve-checker", "parser": build_parser,
            "run": run_cmd,
            "help": "run the checker daemon (shape-binned batch "
                    "checking on a warm chip)",
            "description":
                "Persistent linearizability-checker daemon "
                "(doc/service.md): accepts histories over the wire, "
                "bins them by traced shape, and decides same-shape "
                "bins as single vmapped device programs. The results "
                "web browser is the separate `serve` command."}


@command
def service_stats_cmd() -> dict:
    """Print the checker daemon's stats: live over the wire when the
    daemon answers, else the last stats snapshot it wrote
    (JEPSEN_TPU_SERVICE_STATS) — so the command works during AND after
    a run."""

    def build_parser(p: argparse.ArgumentParser):
        p.add_argument("--host", default="127.0.0.1")
        p.add_argument("--port", "-p", type=int, default=None)
        p.add_argument("--file", help="read this stats snapshot "
                                      "instead of asking a live "
                                      "daemon")
        p.add_argument("--json", action="store_true",
                       help="print the BARE stats dict (machine "
                            "consumers; default output wraps it with "
                            "source/addr provenance)")

    def run_cmd(opts: argparse.Namespace) -> int:
        import json

        from jepsen_tpu.obs import load_json_snapshot
        from jepsen_tpu.service import daemon as service_daemon

        if not opts.file:
            try:
                from jepsen_tpu.service.protocol import CheckerClient

                port = opts.port if opts.port is not None \
                    else service_daemon.default_port()
                client = CheckerClient(opts.host, port, timeout=5.0)
                stats = client.stats()
                client.close()
                out = stats if opts.json else {
                    "source": "live", "addr": f"{opts.host}:{port}",
                    "stats": stats}
                print(json.dumps(out, indent=1, sort_keys=True))
                return EXIT_OK
            except (ConnectionError, OSError):
                pass   # no live daemon: fall back to the snapshot
        path = opts.file or service_daemon.stats_path()
        snap, err = load_json_snapshot(path)
        if snap is None:
            print(f"no live daemon and no readable stats snapshot "
                  f"at {path!r}: {err}", file=sys.stderr)
            return EXIT_ERROR
        out = snap if opts.json else {"source": "snapshot",
                                      "path": path, "stats": snap}
        print(json.dumps(out, indent=1, sort_keys=True))
        return EXIT_OK

    return {"name": "service-stats", "parser": build_parser,
            "run": run_cmd,
            "help": "print checker-daemon stats (live, or the last "
                    "snapshot)",
            "description":
                "Checker-daemon observability: queue depth, per-bin "
                "depths, batch occupancy, verdict counters, latency "
                "p50/p99, XLA compile meter. Tries the live daemon "
                "first, then the stats snapshot file."}


@command
def fleet_bench_cmd() -> dict:
    """Run the fleet scaling bench (jepsen_tpu.service.fleet_bench):
    the seeded mixed workload (checks + streams + txn) at workers=1
    then workers=8 on the 8-device CPU mesh, with verdict parity and
    the 8v1 throughput ratio in the JSON artifact."""

    def build_parser(p: argparse.ArgumentParser):
        pass

    def run_cmd(opts: argparse.Namespace) -> int:
        from jepsen_tpu.service import fleet_bench

        return fleet_bench.main()

    return {"name": "fleet-bench", "parser": build_parser,
            "run": run_cmd,
            "help": "fleet scaling bench: workers=1 vs workers=8 "
                    "mixed traffic on the CPU mesh (chip-free)",
            "description":
                "Drives the same seeded mixed workload (many-bin "
                "check requests, concurrent wire stream sessions, a "
                "txn minority) through an in-process daemon at "
                "workers=1 and workers=8, audits every verdict "
                "against the CPU oracle, and prints histories/s, the "
                "8v1 ratio, per-device occupancy, and stream batch "
                "occupancy. Appends a service-fleet-bench perf-ledger "
                "record. Chip-free: forces the CPU platform itself."}


@command
def journal_cmd() -> dict:
    """Manage the checker daemon's durable request journal
    (jepsen_tpu.service.journal, doc/service.md § Fleet): ``list``
    prints its state (unsettled admits are requests a crash left
    undecided), ``replay`` re-decides them offline through the
    daemon's own replay machinery, ``gc`` compacts settled pairs."""

    def build_parser(p: argparse.ArgumentParser):
        p.add_argument("action", choices=["list", "replay", "gc"])
        p.add_argument("--journal", help="journal path (default: "
                                         "JEPSEN_TPU_SERVICE_JOURNAL)")
        p.add_argument("--json", action="store_true",
                       help="machine-readable output")
        p.add_argument("--timeout", type=float, default=600.0,
                       help="replay: max seconds to wait for every "
                            "unsettled entry to re-decide")

    def run_cmd(opts: argparse.Namespace) -> int:
        import json
        import time

        from jepsen_tpu.service import journal as journal_mod

        path = opts.journal or journal_mod.journal_path()
        if not path:
            print("no journal: set JEPSEN_TPU_SERVICE_JOURNAL or "
                  "pass --journal", file=sys.stderr)
            return EXIT_ERROR
        j = journal_mod.Journal(path)
        if opts.action == "list":
            stats = j.stats()
            recs = journal_mod.describe(j.load())
            if opts.json:
                print(json.dumps({"stats": stats, "records": recs},
                                 indent=1, default=str))
                return EXIT_OK
            print(f"journal {path}: depth {stats['journal_depth']} "
                  f"unsettled, {stats['journal_settles']} settled, "
                  f"{stats['journal_streams_open']} stream session(s) "
                  f"open, {stats['journal_torn_lines']} torn line(s)")
            for r in recs:
                if r["kind"] in ("check", "txn-check"):
                    mark = "settled" if r["settled"] else "UNSETTLED"
                    print(f"  seq {r['seq']}  {r['kind']}  "
                          f"{r['model']}  {r['ops']} ops  fp "
                          f"{r['fp']}  {mark}")
                else:
                    print(f"  seq {r['seq']}  {r['kind']}  "
                          f"{r.get('sid')}  "
                          f"{r.get('model', r.get('how', ''))}")
            return EXIT_OK
        if opts.action == "gc":
            r = j.gc()
            print(f"journal gc: kept {r['kept']} record(s), dropped "
                  f"{r['dropped']}")
            return EXIT_OK
        # replay: the daemon's OWN replay machinery (an ephemeral-port
        # CheckerService that never advertises), so offline re-decides
        # cannot drift from restart re-decides.
        depth = j.depth()
        j.close()
        if depth == 0:
            print("journal replay: nothing unsettled")
            return EXIT_OK
        from jepsen_tpu.service.daemon import CheckerService

        svc = CheckerService("127.0.0.1", 0, journal=path).start()
        deadline = time.time() + opts.timeout
        try:
            while time.time() < deadline \
                    and svc._journal.depth() > 0:
                time.sleep(0.2)
            left = svc._journal.depth()
        finally:
            svc.stop()
        print(f"journal replay: re-decided {depth - left} of {depth} "
              f"unsettled entr{'y' if depth == 1 else 'ies'}"
              + (f" ({left} still unsettled)" if left else ""))
        return EXIT_OK if left == 0 else EXIT_UNKNOWN

    return {"name": "journal", "parser": build_parser, "run": run_cmd,
            "help": "list/replay/gc the checker daemon's request "
                    "journal",
            "description":
                "Durable request journal (doc/service.md § Fleet): "
                "every admitted check is journaled before it is "
                "decided; a restarted daemon (or `journal replay`) "
                "re-decides the unsettled tail. `gc` compacts "
                "settled pairs; JEPSEN_TPU_SERVICE_JOURNAL names the "
                "file."}


@command
def analyze_cmd() -> dict:
    """Re-run a checker offline on a saved history — the TPU build's
    first-class path: record once, re-check on device (the seam noted in
    SURVEY.md §5 checkpoint/resume)."""

    def build_parser(p: argparse.ArgumentParser):
        p.add_argument("test_name")
        p.add_argument("timestamp", nargs="?",
                       help="defaults to the latest run")
        p.add_argument("--store", default="store")
        p.add_argument("--model", default="cas-register",
                       choices=["cas-register", "register", "mutex"])
        p.add_argument("--algorithm", default="competition",
                       choices=["tpu", "cpu", "competition"])

    def run_cmd(opts: argparse.Namespace) -> int:
        import json

        from jepsen_tpu import models as m
        from jepsen_tpu import store
        from jepsen_tpu.lin import analysis

        runs = store.tests(opts.test_name, base=opts.store)
        if not runs:
            print(f"no runs found for {opts.test_name!r} in {opts.store}",
                  file=sys.stderr)
            return EXIT_ERROR
        ts = opts.timestamp or sorted(runs)[-1]
        test = runs[ts]() if ts in runs else None
        if test is None:
            print(f"no run {ts!r}", file=sys.stderr)
            return EXIT_ERROR
        model = {"cas-register": m.cas_register, "register": m.register,
                 "mutex": m.mutex}[opts.model]()
        result = analysis(model, test["history"],
                          algorithm=opts.algorithm)
        print(json.dumps({k: v for k, v in result.items()
                          if k in ("valid?", "analyzer", "op", "error")},
                         default=repr, indent=2))
        valid = result.get("valid?")
        return (EXIT_OK if valid is True else
                EXIT_INVALID if valid is False else EXIT_UNKNOWN)

    return {"name": "analyze", "parser": build_parser, "run": run_cmd,
            "help": "re-check a saved history (optionally on device)",
            "description":
                "Re-run a linearizability checker offline on a saved "
                "run's history, on the cpu/tpu/competition engines."}


@command
def quarantine_cmd() -> dict:
    """Manage the fault-shape quarantine ledger
    (jepsen_tpu.lin.supervise): the persistent record of traced program
    shapes that faulted or wedged the TPU runtime, which routes future
    runs straight to each shape's proven fallback rung. ``list`` prints
    it, ``clear`` removes entries (all, or ``--shape`` ones) after an
    engine fix, ``diff --before SNAPSHOT`` prints the delta against a
    saved copy (what ``make probe-config5`` runs after its probe)."""

    def build_parser(p: argparse.ArgumentParser):
        p.add_argument("action", choices=["list", "clear", "diff"])
        p.add_argument("--ledger", help="ledger path (default: the "
                       "engines' JEPSEN_TPU_QUARANTINE resolution)")
        p.add_argument("--shape", action="append",
                       help="shape key(s) for clear; repeatable")
        p.add_argument("--before",
                       help="for diff (required there): a prior copy "
                            "of the ledger file")

    def run_cmd(opts: argparse.Namespace) -> int:
        import json

        from jepsen_tpu.lin import supervise

        path = opts.ledger or supervise.ledger_path()
        if opts.action == "list":
            shapes = supervise.load_ledger(path)
            if not shapes:
                print(f"quarantine ledger empty ({path})")
                return EXIT_OK
            # Crash evidence (fault/wedge — routes future runs) prints
            # apart from the static gate's PREDICTIONS (reason=static:
            # observability; routing-inert once the gate is off).
            crash = {k: e for k, e in shapes.items()
                     if e.get("reason") != "static" or e.get("faulted")}
            static = {k: e for k, e in shapes.items()
                      if k not in crash}
            for k in sorted(crash):
                e = crash[k]
                print(f"{k}  reason={e.get('reason')} "
                      f"count={e.get('count')} last={e.get('last')}")
            if static:
                print(f"static (gate-predicted, JEPSEN_TPU_STATIC_GATE"
                      f" — not crash evidence): {len(static)} shape(s)")
                for k in sorted(static):
                    e = static[k]
                    print(f"  {k}  count={e.get('count')} "
                          f"last={e.get('last')} "
                          f"detail={e.get('detail', '')[:80]}")
            return EXIT_OK
        if opts.action == "clear":
            n = supervise.clear_ledger(keys=opts.shape, path=path)
            print(f"cleared {n} quarantined shape(s)")
            return EXIT_OK
        # diff — an unreadable/malformed --before must fail loudly:
        # silently treating it as empty would report every
        # long-standing entry as "newly faulted", the exact misread
        # the probe-config5 delta exists to prevent.
        if not opts.before:
            print("quarantine diff requires --before SNAPSHOT",
                  file=sys.stderr)
            return EXIT_USAGE
        try:
            with open(opts.before) as fh:
                before = json.load(fh).get("shapes", {})
        except (OSError, ValueError) as e:
            print(f"cannot read --before snapshot {opts.before!r}: "
                  f"{e}", file=sys.stderr)
            return EXIT_ERROR
        delta = supervise.ledger_delta(before, path=path)
        if not delta:
            print("quarantine delta: none")
            return EXIT_OK
        print(f"quarantine delta: {len(delta)} shape(s) newly faulted")
        for k in sorted(delta):
            e = delta[k]
            print(f"  {k}  reason={e.get('reason')} "
                  f"count={e.get('count')}")
        return EXIT_OK

    return {"name": "quarantine", "parser": build_parser,
            "run": run_cmd,
            "help": "list/clear/diff the fault-shape quarantine ledger",
            "description":
                "Manage the persistent record of traced program "
                "shapes that faulted/wedged the TPU runtime "
                "(.jax_cache/quarantine.json; doc/env.md "
                "JEPSEN_TPU_QUARANTINE)."}


@command
def lint_cmd() -> dict:
    """Run the repo contract linter (jepsen_tpu.analysis.lint): the
    CLAUDE.md architecture invariants — iteration ceilings, env-knob
    doc drift, the wire suites' :info-never-:fail rule, Pallas
    module-constant hygiene, quick-tier compile markers — as a
    zero-findings gate (``make lint``; doc/analysis.md)."""

    def build_parser(p: argparse.ArgumentParser):
        p.add_argument("--root", help="checkout root to lint "
                                      "(default: this package's "
                                      "checkout)")
        p.add_argument("--json", action="store_true",
                       help="findings as JSON records")

    def run_cmd(opts: argparse.Namespace) -> int:
        import json

        from jepsen_tpu.analysis import lint as lint_mod

        findings = lint_mod.lint_repo(opts.root)
        if opts.json:
            print(json.dumps([vars(f) for f in findings], indent=1))
        else:
            print(lint_mod.render(findings))
        return EXIT_OK if not findings else EXIT_INVALID

    return {"name": "lint", "parser": build_parser, "run": run_cmd,
            "help": "run the repo contract linter (zero findings = "
                    "clean)",
            "description":
                "Static repo contracts (doc/analysis.md): "
                "lax.while_loop iteration ceilings in lin/+txn/, "
                "JEPSEN_TPU_* <-> doc/env.md drift both ways, wire "
                "suites' :info-never-:fail exception rule, no "
                "module-level jnp constants in Pallas modules, "
                "quick-tier compiles markers. Exit 1 on findings."}


@command
def host_stats_cmd() -> dict:
    """Print a (running or finished) check's host-row executor stats
    and run telemetry from the obs registry snapshot — the CLAUDE.md
    triage habit ("check host-stats and quarantine list BEFORE the env
    knobs") as a first-class command instead of digging the verdict
    dict out of an artifact."""

    def build_parser(p: argparse.ArgumentParser):
        p.add_argument("--file", help="run-telemetry snapshot path "
                                      "(default: the engines' "
                                      "JEPSEN_TPU_OBS_SNAPSHOT "
                                      "resolution)")
        p.add_argument("--json", action="store_true",
                       help="print the raw snapshot JSON")

    def run_cmd(opts: argparse.Namespace) -> int:
        import json

        from jepsen_tpu.obs import load_json_snapshot, metrics

        path = opts.file or metrics.snapshot_path()
        snap, err = load_json_snapshot(path)
        if snap is None:
            print(f"no readable run-telemetry snapshot at {path!r}: "
                  f"{err} — run a check with the engines loaded "
                  f"(the snapshot writes every "
                  f"JEPSEN_TPU_OBS_EVERY_S seconds)", file=sys.stderr)
            return EXIT_ERROR
        if opts.json:
            print(json.dumps(snap, indent=1, sort_keys=True,
                             default=str))
            return EXIT_OK
        run = snap.get("run") or {}
        print(f"run: {run.get('run', '?')}  updated "
              f"{snap.get('updated', '?')}  pid {snap.get('pid')}")
        row, total = run.get("row"), run.get("total_rows")
        if row is not None:
            pct = f" ({100.0 * row / total:.1f}%)" if total else ""
            print(f"  row {row}/{total or '?'}{pct}  "
                  f"frontier {run.get('frontier', '?')}  "
                  f"rows/s {run.get('rows_per_sec', '?')}  "
                  f"eta_s {run.get('eta_s', '?')}")
        print(f"  xla compiles {snap.get('xla_compiles', 0)} "
              f"({snap.get('xla_compile_s', 0)} s)")
        for name in sorted(snap.get("views") or {}):
            print(f"[{name}]")
            for k, v in sorted((snap["views"][name] or {}).items()):
                print(f"  {k} = {v}")
        events = snap.get("events") or []
        if events:
            print("[events]")
            for e in events[-16:]:
                rest = {k: v for k, v in e.items()
                        if k not in ("t", "kind")}
                print(f"  {e.get('t')} {e.get('kind')} {rest}")
        return EXIT_OK

    return {"name": "host-stats", "parser": build_parser,
            "run": run_cmd,
            "help": "print a run's host-stats + telemetry (from the "
                    "obs registry snapshot)",
            "description":
                "Engine observability (doc/observability.md): the "
                "host-row executor's episode/dispatch/waste counters, "
                "run progress gauges (row, frontier, rows/s, ETA), "
                "and the watchdog/quarantine event feed, read from "
                "the run-telemetry snapshot the engines write "
                "(JEPSEN_TPU_OBS_SNAPSHOT). web.py /run renders the "
                "same file."}


@command
def trace_cmd() -> dict:
    """The flight recorder's attribution outputs (doc/observability.md):
    ``trace report`` prints the where-did-the-time-go table from a
    traced run's JSONL spill; ``trace export --chrome`` converts it to
    Chrome/Perfetto trace-event JSON."""

    def build_parser(p: argparse.ArgumentParser):
        p.add_argument("action", choices=["report", "export"])
        p.add_argument("--file", help="trace JSONL path (default: the "
                                      "tracer's JEPSEN_TPU_TRACE_FILE "
                                      "resolution)")
        p.add_argument("--json", action="store_true",
                       help="report as JSON instead of the table")
        p.add_argument("--chrome", action="store_true",
                       help="export format: Chrome/Perfetto "
                            "trace-event JSON (the only format today; "
                            "the flag names it for forward compat)")
        p.add_argument("--out", "-o",
                       help="export output path (default: stdout)")

    def run_cmd(opts: argparse.Namespace) -> int:
        import json

        from jepsen_tpu.obs import report, trace

        path = opts.file or trace.trace_file()
        if path is None:
            print("tracing file disabled (JEPSEN_TPU_TRACE_FILE=0) "
                  "and no --file given", file=sys.stderr)
            return EXIT_ERROR
        events = report.load(path)
        if not events:
            print(f"no trace events at {path!r} — run with "
                  f"JEPSEN_TPU_TRACE=1 first (doc/observability.md)",
                  file=sys.stderr)
            return EXIT_ERROR
        if opts.action == "report":
            agg = report.attribution(events)
            if opts.json:
                print(json.dumps(agg, indent=1, sort_keys=True))
            else:
                print(f"trace file: {path}")
                print(report.render(agg))
            return EXIT_OK
        # export (--chrome is the only format; accepted for clarity)
        chrome = report.to_chrome(events)
        if opts.out:
            with open(opts.out, "w") as fh:
                json.dump(chrome, fh)
            print(f"wrote {len(chrome['traceEvents'])} trace events "
                  f"to {opts.out} (load in ui.perfetto.dev or "
                  f"chrome://tracing)")
        else:
            print(json.dumps(chrome))
        return EXIT_OK

    return {"name": "trace", "parser": build_parser, "run": run_cmd,
            "help": "report/export a traced run's timeline "
                    "(JEPSEN_TPU_TRACE=1)",
            "description":
                "Flight-recorder attribution (doc/observability.md): "
                "`trace report` prints per-site x per-cap wall "
                "seconds, compile time, tunnel-overhead estimate, "
                "wasted-rung cost, and the per-episode dispatch "
                "histogram (dispatches/episode — the episode "
                "scheduler's acceptance metric); `trace export "
                "--chrome` emits Perfetto-loadable trace-event "
                "JSON."}


@command
def perf_cmd() -> dict:
    """The cross-run perf ledger (jepsen_tpu.obs.ledger,
    doc/observability.md § Perf ledger): ``report`` prints the
    per-(probe, platform) trend table, ``diff --before SNAPSHOT``
    prints records appended since a prior copy (the ``quarantine
    diff`` precedent; ``make probe-config5`` runs it), and ``gate`` is
    the CI-consumable regression sentinel — nonzero exit on a verdict
    flip, a wall-time regression past ``JEPSEN_TPU_PERF_GATE_FRAC`` x
    the trailing median, new quarantine entries, or
    dispatches/episode growth."""

    def build_parser(p: argparse.ArgumentParser):
        p.add_argument("action", choices=["report", "diff", "gate"])
        p.add_argument("--ledger", help="perf ledger path (default: "
                                        "JEPSEN_TPU_PERF_LEDGER "
                                        "resolution)")
        p.add_argument("--probe", help="restrict to one probe tag")
        p.add_argument("--before",
                       help="for diff (required there): a prior copy "
                            "of the ledger file")
        p.add_argument("--frac", type=float, default=None,
                       help="gate: regression threshold override "
                            "(default JEPSEN_TPU_PERF_GATE_FRAC, "
                            "1.5)")
        p.add_argument("--json", action="store_true",
                       help="machine-readable output")

    def run_cmd(opts: argparse.Namespace) -> int:
        import json

        from jepsen_tpu.obs import ledger as ledger_mod

        path = opts.ledger or ledger_mod.ledger_path()
        if path is None:
            print("perf ledger disabled (JEPSEN_TPU_PERF_LEDGER=0) "
                  "and no --ledger given", file=sys.stderr)
            return EXIT_ERROR
        records = ledger_mod.load(path)
        if opts.probe:
            records = [r for r in records
                       if r.get("probe") == opts.probe]
        if opts.action == "report":
            if not records:
                print(f"no perf-ledger records at {path!r} — run a "
                      f"bench probe or any `make *-smoke` first "
                      f"(doc/observability.md § Perf ledger)",
                      file=sys.stderr)
                return EXIT_ERROR
            rows = ledger_mod.trend(records)
            if opts.json:
                print(json.dumps(rows, indent=1, sort_keys=True))
            else:
                print(f"perf ledger: {path} ({len(records)} "
                      f"record(s))")
                print(ledger_mod.render_trend(rows))
            return EXIT_OK
        if opts.action == "diff":
            # An unreadable --before must fail loudly (the quarantine
            # diff precedent): silently treating it as empty would
            # report every long-standing record as new.
            if not opts.before:
                print("perf diff requires --before SNAPSHOT",
                      file=sys.stderr)
                return EXIT_USAGE
            try:
                # Actually open it: exists() passes for a directory
                # or a permission-denied file, which load() would
                # silently treat as empty — the bogus full delta.
                with open(opts.before) as fh:
                    fh.read(1)
            except OSError as e:
                print(f"cannot read --before snapshot "
                      f"{opts.before!r}: {e}", file=sys.stderr)
                return EXIT_ERROR
            before = ledger_mod.load(opts.before)
            if opts.probe:
                before = [r for r in before
                          if r.get("probe") == opts.probe]
            new = ledger_mod.diff(before, records)
            if opts.json:
                print(json.dumps(new, indent=1, default=str))
            else:
                print(ledger_mod.render_diff(
                    new, ledger_mod.trend(records)))
            return EXIT_OK
        # gate — zero matching records must fail LOUDLY, not pass: a
        # wrong --ledger path or a typo'd --probe tag would otherwise
        # keep CI green forever with nothing under guard.
        if not records:
            print(f"perf gate: no records"
                  + (f" for probe {opts.probe!r}" if opts.probe
                     else "")
                  + f" at {path!r} — nothing is under guard "
                  f"(wrong path or tag?)", file=sys.stderr)
            return EXIT_ERROR
        # records are already --probe-filtered above: the cli owns
        # the filter, gate() sees the final list. A malformed
        # JEPSEN_TPU_PERF_GATE_FRAC fails LOUDLY with a clean message
        # (not a traceback) — silently falling back to the default
        # would gate at a threshold the operator did not choose,
        # while trace's cosmetic MAX_MB knob may safely self-default.
        try:
            findings = ledger_mod.gate(records, frac=opts.frac)
        except ValueError as e:
            print(f"malformed JEPSEN_TPU_PERF_GATE_FRAC (or --frac): "
                  f"{e}", file=sys.stderr)
            return EXIT_ERROR
        if opts.json:
            print(json.dumps(findings, indent=1, sort_keys=True))
        else:
            print(ledger_mod.render_gate(findings))
        return EXIT_OK if not findings else EXIT_INVALID

    return {"name": "perf", "parser": build_parser, "run": run_cmd,
            "help": "report/diff/gate the cross-run perf ledger "
                    "(regression sentinel)",
            "description":
                "Cross-run perf ledger (doc/observability.md § Perf "
                "ledger): every bench probe rung, probe-config5, and "
                "chip-free smoke appends one record (git sha, "
                "platform, env fingerprint, wall/verdict/host-stats/"
                "quarantine delta). `report` prints the trend table, "
                "`diff --before` the delta since a snapshot, `gate` "
                "exits nonzero on a verdict flip / wall regression / "
                "new quarantine entries / dispatch growth."}


def run(commands, argv=None) -> int:
    """Dispatch subcommands (cli.clj:201-276). Returns the exit code; the
    `main` wrapper calls sys.exit with it."""
    from jepsen_tpu.util import enable_compile_cache

    enable_compile_cache()
    if isinstance(commands, dict) and "name" in commands:
        commands = [commands]
    parser = argparse.ArgumentParser(prog="jepsen-tpu")
    subs = parser.add_subparsers(dest="subcommand")
    for cmd in commands:
        sp = subs.add_parser(
            cmd["name"], help=cmd.get("help"),
            description=cmd.get("description", cmd.get("help")))
        cmd["parser"](sp)
        sp.set_defaults(_run=cmd["run"])

    try:
        opts = parser.parse_args(argv)
    except SystemExit as e:
        return EXIT_USAGE if e.code not in (0,) else EXIT_OK
    if not getattr(opts, "_run", None):
        parser.print_help()
        return EXIT_USAGE
    try:
        return opts._run(opts)
    except UsageError as e:
        print(f"usage error: {e}", file=sys.stderr)
        return EXIT_USAGE
    except Exception:
        traceback.print_exc()
        return EXIT_ERROR


def main(commands, argv=None) -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s - %(message)s")
    sys.exit(run(commands, argv))


def _demo_test_fn(options: dict) -> dict:
    """The built-in demo test: in-memory CAS register through the full
    runner (what `python -m jepsen_tpu.cli test` runs with no suite)."""
    from jepsen_tpu import generator as g
    from jepsen_tpu import models
    from jepsen_tpu import tests_support as ts
    from jepsen_tpu.checker import timeline

    reg = ts.AtomRegister()
    return {
        "name": "demo-cas",
        "nodes": options["nodes"],
        "transport": "dummy",
        "concurrency": options["concurrency"],
        "store-base": options["store-base"],
        "client": ts.AtomClient(reg, latency=0.002),
        "generator": g.clients(
            g.time_limit(min(options.get("time-limit", 10), 10),
                         g.stagger(0.005, g.cas(5)))),
        "model": models.cas_register(),
        # cpu engine: the demo shouldn't contend for the TPU chip
        "checker": checker_ns.compose({
            "linear": checker_ns.linearizable("cpu"),
            "timeline": timeline.checker(),
            "perf": checker_ns.perf()}),
    }


def main_default(argv=None) -> None:
    """The bare `jepsen-tpu` console script (pyproject entry point):
    demo test + every registered standard command, like
    `python -m jepsen_tpu.cli`."""
    main([single_test_cmd(_demo_test_fn)] + standard_commands(), argv)


if __name__ == "__main__":
    main_default()
