"""Self-healing connection wrappers.

Re-design of `jepsen/src/jepsen/reconnect.clj` (129 LoC): a wrapper around
a connection with an RW-lock-guarded slot, auto close/reopen on error —
failure recovery for both SSH and DB client connections.
"""

from __future__ import annotations

import threading
from typing import Any, Callable


class Wrapper:
    """Holds a connection built by ``open_fn``; ``with_conn`` runs a
    function against it, reopening on failure (reconnect.clj:17-33,
    98-129)."""

    def __init__(self, open_fn: Callable[[], Any],
                 close_fn: Callable[[Any], None] | None = None,
                 log: str | None = None):
        self.open_fn = open_fn
        self.close_fn = close_fn or (lambda conn: None)
        self.log = log
        self._conn: Any = None
        self._lock = threading.RLock()

    def open(self) -> "Wrapper":
        with self._lock:
            if self._conn is None:
                self._conn = self.open_fn()
        return self

    def conn(self):
        with self._lock:
            if self._conn is None:
                self.open()
            return self._conn

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self.close_fn(self._conn)
                finally:
                    self._conn = None

    def reopen(self) -> None:
        """Close and reopen (reconnect.clj:85-95)."""
        with self._lock:
            self.close()
            self.open()

    def with_conn(self, f: Callable[[Any], Any]):
        """Run f(conn); on error, close the connection (so the next call
        reopens) and re-raise (reconnect.clj:98-129)."""
        try:
            return f(self.conn())
        except Exception:
            try:
                self.close()
            except Exception:
                pass
            raise


def wrapper(open_fn, close_fn=None, log=None) -> Wrapper:
    return Wrapper(open_fn, close_fn, log)
