"""Functional abstract models of database behavior.

Pure state machines with a ``step(op) -> model | Inconsistent`` transition,
mirroring the reference's `jepsen/src/jepsen/model.clj` (which wraps
knossos.model/Model + inconsistent, model.clj:4-11):

- :class:`NoOp`           — model.clj:13-19
- :class:`CASRegister`    — model.clj:21-40
- :class:`Register`       — write/read only (knossos.model/register)
- :class:`Mutex`          — model.clj:42-56
- :class:`SetModel`       — model.clj:58-71
- :class:`UnorderedQueue` — model.clj:73-85
- :class:`FIFOQueue`      — model.clj:87-105

Each model here is the *semantic reference*; the vmap-able device kernels the
TPU linearizability search uses live in :mod:`jepsen_tpu.models.kernels` and
are parity-tested against these.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Tuple


@dataclass(frozen=True)
class Inconsistent:
    """A sentinel transition result marking an impossible op
    (knossos.model/inconsistent, used at reference model.clj:29,34)."""

    msg: str

    @property
    def is_inconsistent(self) -> bool:
        return True


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


def is_inconsistent(x) -> bool:
    return isinstance(x, Inconsistent)


class Model:
    """Base for abstract models (knossos.model/Model)."""

    def step(self, op) -> "Model | Inconsistent":
        raise NotImplementedError

    @property
    def is_inconsistent(self) -> bool:
        return False


@dataclass(frozen=True)
class NoOp(Model):
    """Always returns itself, unchanged (reference model.clj:13-19)."""

    def step(self, op):
        return self


noop = NoOp()


@dataclass(frozen=True)
class CASRegister(Model):
    """A compare-and-set register (reference model.clj:21-40).

    - ``write v``     — always succeeds, value becomes v
    - ``cas [cur, new]`` — succeeds iff value == cur, becomes new
    - ``read v``      — succeeds iff v is None (unknown) or v == value
    """

    value: Any = None

    def step(self, op):
        f = op.f
        if f == "write":
            return CASRegister(op.value)
        if f == "cas":
            cur, new = op.value
            if cur == self.value:
                return CASRegister(new)
            return inconsistent(f"can't CAS {self.value} from {cur} to {new}")
        if f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(
                f"can't read {op.value} from register {self.value}")
        return inconsistent(f"unknown op f={f}")


def cas_register(value=None) -> CASRegister:
    return CASRegister(value)


@dataclass(frozen=True)
class Register(Model):
    """A read/write register without CAS (knossos.model/register; the
    reference's BASELINE config #1 shape)."""

    value: Any = None

    def step(self, op):
        f = op.f
        if f == "write":
            return Register(op.value)
        if f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(
                f"can't read {op.value} from register {self.value}")
        return inconsistent(f"unknown op f={f}")


def register(value=None) -> Register:
    return Register(value)


@dataclass(frozen=True)
class Mutex(Model):
    """A single mutex responding to acquire/release
    (reference model.clj:42-56)."""

    locked: bool = False

    def step(self, op):
        f = op.f
        if f == "acquire":
            if self.locked:
                return inconsistent("already held")
            return Mutex(True)
        if f == "release":
            if not self.locked:
                return inconsistent("not held")
            return Mutex(False)
        return inconsistent(f"unknown op f={f}")


def mutex() -> Mutex:
    return Mutex(False)


@dataclass(frozen=True)
class SetModel(Model):
    """A set responding to add/read (reference model.clj:58-71)."""

    s: frozenset = field(default_factory=frozenset)

    def step(self, op):
        f = op.f
        if f == "add":
            return SetModel(self.s | {op.value})
        if f == "read":
            try:
                observed = set(op.value) if op.value is not None else None
            except TypeError:
                observed = None
            if observed is not None and observed == set(self.s):
                return self
            return inconsistent(f"can't read {op.value!r} from {set(self.s)!r}")
        return inconsistent(f"unknown op f={f}")


def set_model() -> SetModel:
    return SetModel()


@dataclass(frozen=True)
class UnorderedQueue(Model):
    """A queue which does not order its pending elements — a multiset
    (reference model.clj:73-85)."""

    pending: tuple = ()  # sorted multiset rep, kept canonical for equality

    def step(self, op):
        f = op.f
        if f == "enqueue":
            return UnorderedQueue(_multiset_add(self.pending, op.value))
        if f == "dequeue":
            if op.value in self.pending:
                return UnorderedQueue(_multiset_remove(self.pending, op.value))
            return inconsistent(f"can't dequeue {op.value}")
        return inconsistent(f"unknown op f={f}")


def _multiset_add(t: tuple, v) -> tuple:
    return tuple(sorted(list(t) + [v], key=repr))


def _multiset_remove(t: tuple, v) -> tuple:
    out = list(t)
    out.remove(v)
    return tuple(out)


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


@dataclass(frozen=True)
class FIFOQueue(Model):
    """A FIFO queue (reference model.clj:87-105)."""

    pending: tuple = ()

    def step(self, op):
        f = op.f
        if f == "enqueue":
            return FIFOQueue(self.pending + (op.value,))
        if f == "dequeue":
            if not self.pending:
                return inconsistent(
                    f"can't dequeue {op.value} from empty queue")
            if self.pending[0] == op.value:
                return FIFOQueue(self.pending[1:])
            return inconsistent(f"can't dequeue {op.value}")
        return inconsistent(f"unknown op f={f}")


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()


def multiset(xs=()) -> Counter:
    """Multiset helper mirroring the reference's multiset.core dependency
    (project.clj:15), used by the total-queue checker."""
    return Counter(xs)
