"""Vmap-able JAX step kernels for the abstract models.

These are the device twins of the Python models in
:mod:`jepsen_tpu.models` (reference model.clj semantics), written as pure
branchless int ops so the TPU linearizability search
(:mod:`jepsen_tpu.lin.bfs`) can evaluate *millions of candidate transitions
per step* via vmap over an HBM-resident frontier: frontier-config x pending-op
legality masks are exactly `ok` bits from these kernels.

Conventions:

- ``f`` is an interned function id (:data:`F_READ` ...).
- Values are interned int32 ids (interning in :mod:`jepsen_tpu.lin.prepare`);
  :data:`NIL` is the sentinel for nil/unknown (a read invoked with value nil
  matches any state — reference model.clj:31-32).
- Model state is an int32 vector of fixed width ``state_width``.
- ``step(state, f, v) -> (ok, new_state)`` with no Python control flow, so a
  single compiled kernel evaluates the cross product (configs x candidate ops)
  on the MXU-adjacent vector units without retracing.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Callable

import jax.numpy as jnp
import numpy as np

# Interned function ids, shared host<->device.
F_READ = 0
F_WRITE = 1
F_CAS = 2
F_ACQUIRE = 3
F_RELEASE = 4
# Universal no-op: legal in every model, state unchanged. Used by the BFS
# kernel's identity padding rows (bucketing history length to a few static
# shapes so XLA compiles once per bucket, not once per history).
F_NOOP = 5
F_ENQUEUE = 6
F_DEQUEUE = 7
F_ADD = 8

F_IDS = {"read": F_READ, "write": F_WRITE, "cas": F_CAS,
         "acquire": F_ACQUIRE, "release": F_RELEASE,
         "enqueue": F_ENQUEUE, "dequeue": F_DEQUEUE, "add": F_ADD}

# Sentinel for nil/unknown values. Never produced by interning.
NIL = np.int32(-(2 ** 31))

# Kernel families whose one-word state is a bounded non-negative int
# (interned ids or a bitmask; NIL remapped to a dedicated id): eligible
# for the dense config-space bitmap engine (lin/dense.py) and the
# sparse engine's packed-u32 sort keys (lin/bfs.py). The register and
# mutex families range over the intern table; a one-word set ranges
# over element-bitmask values, so its bound rides on the kernel itself
# (state_bound) — :func:`packed_state_bound` is the ONE definition of
# the state-value range all three engines must share (dense plan,
# bfs packed keys, sharded collective dedup). Keep the engines'
# routing in sync via these two names.
PACKED_STATE_KERNELS = ("cas-register", "register", "mutex", "set")


def packed_state_bound(kernel: "KernelModel", n_intern: int) -> int:
    """Exclusive upper bound of a PACKED_STATE_KERNELS kernel's
    one-word state values. The NIL sentinel is remapped to the bound
    itself (nil_id), so packed state ids live in [0, bound] and need
    ``bound.bit_length()`` bits. Intern-ranged kernels (register /
    mutex) bound by the intern table; bitmask kernels (a one-word set)
    carry their own ``state_bound`` (2**n_elements — their state never
    equals NIL, so the remap id is simply never produced)."""
    if kernel.state_bound is not None:
        return kernel.state_bound
    return max(n_intern, 2)

# Kernels whose F_READ legality is EXACTLY "v == NIL or v == state[0]"
# (see _cas_register_step/_register_step). The sparse engine's pure-op
# saturation fast path (lin/bfs.py _closure_pass_keys) bakes this
# predicate into a per-state table; a kernel listed here with different
# read semantics would make that path unsound — keep the definition next
# to the step functions it mirrors.
READ_VALUE_MATCH_KERNELS = ("cas-register", "register")

# Max value words per op: cas carries [cur, new]; everything else uses v[0].
VALUE_WIDTH = 2


@dataclass(frozen=True)
class KernelModel:
    """A model compiled for the device frontier search."""

    name: str
    state_width: int
    init_state: Callable[[], np.ndarray]  # initial packed state (host)
    step: Callable  # (i32[S], i32, i32[VW]) -> (bool_, i32[S])
    value_width: int = VALUE_WIDTH  # words per op value (VW)
    # Exclusive upper bound of one-word state values for kernels whose
    # state is NOT intern-ranged (see packed_state_bound); None for
    # intern-ranged and multiword kernels.
    state_bound: int | None = None


# --- cas-register (reference model.clj:21-40) -------------------------------

def _cas_register_step(state, f, v):
    cur = state[0]
    is_read = f == F_READ
    is_write = f == F_WRITE
    is_cas = f == F_CAS
    ok = ((is_read & ((v[0] == NIL) | (v[0] == cur)))
          | is_write
          | (is_cas & (v[0] == cur))
          | (f == F_NOOP))
    new = jnp.where(is_write, v[0], jnp.where(is_cas, v[1], cur))
    return ok, state.at[0].set(new)


def _register_step(state, f, v):
    # write/read only (knossos.model/register); cas is never legal.
    cur = state[0]
    is_read = f == F_READ
    is_write = f == F_WRITE
    ok = (is_read & ((v[0] == NIL) | (v[0] == cur))) | is_write \
        | (f == F_NOOP)
    new = jnp.where(is_write, v[0], cur)
    return ok, state.at[0].set(new)


def _mutex_step(state, f, v):
    # reference model.clj:42-56: acquire fails when held, release when not.
    locked = state[0]
    is_acq = f == F_ACQUIRE
    is_rel = f == F_RELEASE
    ok = (is_acq & (locked == 0)) | (is_rel & (locked == 1)) | (f == F_NOOP)
    new = jnp.where(is_acq, jnp.int32(1),
                    jnp.where(is_rel, jnp.int32(0), locked))
    return ok, state.at[0].set(new)


# --- set (reference model.clj:58-71) ----------------------------------------
#
# State is a bitmask over interned element ids, SET_BITS bits per i32 word
# (31, keeping words non-negative so no word ever equals the NIL sentinel).
# ``add e`` sets bit e; ``read S`` succeeds iff the observed mask — packed
# host-side by prepare into the op's value words — equals the state exactly.
# A nil/unpackable read carries all-NIL value words, which no state can
# equal (mask words are non-negative) — matching SetModel, where a read of
# a non-collection is inconsistent.

SET_BITS = 31


@lru_cache(maxsize=None)
def _set_step_fn(n_words):
    def step(state, f, v):
        is_add = f == F_ADD
        is_read = f == F_READ
        e = v[0]
        word = e // SET_BITS
        bit = jnp.uint32(1) << (e % SET_BITS).astype(jnp.uint32)
        add_mask = jnp.where(jnp.arange(n_words) == word,
                             bit.astype(jnp.int32), 0)
        match = jnp.all(state == v[:n_words])
        ok = is_add | (is_read & match) | (f == F_NOOP)
        new = jnp.where(is_add, state | add_mask, state)
        return ok, new

    return step


def set_kernel(n_elements: int, initial_ids=()) -> KernelModel:
    n_words = max(1, -(-n_elements // SET_BITS))

    def init():
        st = np.zeros(n_words, np.int32)
        for e in initial_ids:
            st[e // SET_BITS] |= np.int32(1 << (e % SET_BITS))
        return st

    return KernelModel("set", n_words, init, _set_step_fn(n_words),
                       value_width=max(VALUE_WIDTH, n_words),
                       # One-word sets pack into the dense/sparse
                       # engines' state ids: the word ranges over the
                       # element bitmask, not the intern table.
                       state_bound=(1 << n_elements) if n_words == 1
                       else None)


# --- unordered-queue (reference model.clj:73-85) ----------------------------
#
# A multiset: state is a count per interned value id. Enqueue always
# succeeds; dequeue succeeds iff its value's count is positive.

@lru_cache(maxsize=None)
def _unordered_queue_step_fn(n_values):
    def step(state, f, v):
        is_enq = f == F_ENQUEUE
        is_deq = f == F_DEQUEUE
        onehot = (jnp.arange(n_values) == v[0]).astype(jnp.int32)
        cnt = jnp.sum(state * onehot)
        ok = is_enq | (is_deq & (cnt > 0)) | (f == F_NOOP)
        delta = jnp.where(is_enq, onehot, jnp.where(is_deq, -onehot, 0))
        return ok, state + delta

    return step


def unordered_queue_kernel(n_values: int, initial_ids=()) -> KernelModel:
    n = max(1, n_values)

    def init():
        st = np.zeros(n, np.int32)
        for e in initial_ids:
            st[e] += 1
        return st

    return KernelModel("unordered-queue", n, init,
                       _unordered_queue_step_fn(n))


# Specialization for the common queue-workload shape (reference disque/
# rabbitmq suites enqueue unique ints): when every enqueued value is
# distinct, the pending multiset is a set, packed as a bitmask like the set
# kernel — 31 values per word instead of one count word per value.

@lru_cache(maxsize=None)
def _unordered_unique_step_fn(n_words):
    def step(state, f, v):
        is_enq = f == F_ENQUEUE
        is_deq = f == F_DEQUEUE
        e = v[0]
        word = e // SET_BITS
        bit = jnp.uint32(1) << (e % SET_BITS).astype(jnp.uint32)
        mask_vec = jnp.where(jnp.arange(n_words) == word,
                             bit.astype(jnp.int32), 0)
        has = jnp.any((state & mask_vec) != 0)
        ok = (is_enq & ~has) | (is_deq & has) | (f == F_NOOP)
        new = jnp.where(is_enq, state | mask_vec,
                        jnp.where(is_deq, state & ~mask_vec, state))
        return ok, new

    return step


def unordered_unique_kernel(n_values: int, initial_ids=()) -> KernelModel:
    n_words = max(1, -(-max(1, n_values) // SET_BITS))

    def init():
        st = np.zeros(n_words, np.int32)
        for e in initial_ids:
            st[e // SET_BITS] |= np.int32(1 << (e % SET_BITS))
        return st

    return KernelModel("unordered-unique", n_words, init,
                       _unordered_unique_step_fn(n_words))


# --- fifo-queue (reference model.clj:87-105) --------------------------------
#
# State is [size, buf[0..cap-1]] with buf[0] the front; empty cells are 0
# (canonical, so dedup equality is exact). Enqueue writes at index size;
# dequeue requires buf[0] == v and shifts left.

@lru_cache(maxsize=None)
def _fifo_queue_step_fn(capacity):
    def step(state, f, v):
        is_enq = f == F_ENQUEUE
        is_deq = f == F_DEQUEUE
        size = state[0]
        buf = state[1:]
        front = buf[0]
        ok = ((is_enq & (size < capacity))
              | (is_deq & (size > 0) & (front == v[0]))
              | (f == F_NOOP))
        enq_buf = buf.at[jnp.clip(size, 0, capacity - 1)].set(v[0])
        deq_buf = jnp.concatenate([buf[1:], jnp.zeros(1, jnp.int32)])
        new_buf = jnp.where(is_enq, enq_buf, jnp.where(is_deq, deq_buf, buf))
        new_size = size + jnp.where(is_enq, 1, jnp.where(is_deq, -1, 0))
        return ok, jnp.concatenate([new_size[None], new_buf])

    return step


def fifo_queue_kernel(capacity: int, initial_ids=()) -> KernelModel:
    cap = max(1, capacity)

    def init():
        st = np.zeros(cap + 1, np.int32)
        st[0] = len(initial_ids)
        for i, e in enumerate(initial_ids):
            st[1 + i] = e
        return st

    return KernelModel("fifo-queue", cap + 1, init,
                       _fifo_queue_step_fn(cap))


def cas_register_kernel(initial: int = int(NIL)) -> KernelModel:
    return KernelModel("cas-register", 1,
                       lambda: np.array([initial], np.int32),
                       _cas_register_step)


def register_kernel(initial: int = int(NIL)) -> KernelModel:
    return KernelModel("register", 1,
                       lambda: np.array([initial], np.int32),
                       _register_step)


def mutex_kernel() -> KernelModel:
    return KernelModel("mutex", 1,
                       lambda: np.array([0], np.int32),
                       _mutex_step)


def kernel_for(model) -> KernelModel:
    """Map a Python model instance (jepsen_tpu.models) to its device kernel,
    carrying the instance's current state. Register values still pass
    through value interning in :mod:`jepsen_tpu.lin.prepare` (which owns
    the intern table and overrides init_state with the interned id)."""
    from jepsen_tpu import models as m

    if isinstance(model, m.CASRegister):
        return cas_register_kernel()
    if isinstance(model, m.Register):
        return register_kernel()
    if isinstance(model, m.Mutex):
        kern = mutex_kernel()
        if model.locked:
            return KernelModel(kern.name, kern.state_width,
                               lambda: np.array([1], np.int32), kern.step)
        return kern
    raise ValueError(
        f"no device kernel for model {type(model).__name__}; "
        "device linearizability supports register/cas-register/mutex "
        "(use the CPU checker for other models)")
