"""Vmap-able JAX step kernels for the abstract models.

These are the device twins of the Python models in
:mod:`jepsen_tpu.models` (reference model.clj semantics), written as pure
branchless int ops so the TPU linearizability search
(:mod:`jepsen_tpu.lin.bfs`) can evaluate *millions of candidate transitions
per step* via vmap over an HBM-resident frontier: frontier-config x pending-op
legality masks are exactly `ok` bits from these kernels.

Conventions:

- ``f`` is an interned function id (:data:`F_READ` ...).
- Values are interned int32 ids (interning in :mod:`jepsen_tpu.lin.prepare`);
  :data:`NIL` is the sentinel for nil/unknown (a read invoked with value nil
  matches any state — reference model.clj:31-32).
- Model state is an int32 vector of fixed width ``state_width``.
- ``step(state, f, v) -> (ok, new_state)`` with no Python control flow, so a
  single compiled kernel evaluates the cross product (configs x candidate ops)
  on the MXU-adjacent vector units without retracing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax.numpy as jnp
import numpy as np

# Interned function ids, shared host<->device.
F_READ = 0
F_WRITE = 1
F_CAS = 2
F_ACQUIRE = 3
F_RELEASE = 4
# Universal no-op: legal in every model, state unchanged. Used by the BFS
# kernel's identity padding rows (bucketing history length to a few static
# shapes so XLA compiles once per bucket, not once per history).
F_NOOP = 5

F_IDS = {"read": F_READ, "write": F_WRITE, "cas": F_CAS,
         "acquire": F_ACQUIRE, "release": F_RELEASE}

# Sentinel for nil/unknown values. Never produced by interning.
NIL = np.int32(-(2 ** 31))

# Max value words per op: cas carries [cur, new]; everything else uses v[0].
VALUE_WIDTH = 2


@dataclass(frozen=True)
class KernelModel:
    """A model compiled for the device frontier search."""

    name: str
    state_width: int
    init_state: Callable[[], np.ndarray]  # initial packed state (host)
    step: Callable  # (i32[S], i32, i32[2]) -> (bool_, i32[S])


# --- cas-register (reference model.clj:21-40) -------------------------------

def _cas_register_step(state, f, v):
    cur = state[0]
    is_read = f == F_READ
    is_write = f == F_WRITE
    is_cas = f == F_CAS
    ok = ((is_read & ((v[0] == NIL) | (v[0] == cur)))
          | is_write
          | (is_cas & (v[0] == cur))
          | (f == F_NOOP))
    new = jnp.where(is_write, v[0], jnp.where(is_cas, v[1], cur))
    return ok, state.at[0].set(new)


def _register_step(state, f, v):
    # write/read only (knossos.model/register); cas is never legal.
    cur = state[0]
    is_read = f == F_READ
    is_write = f == F_WRITE
    ok = (is_read & ((v[0] == NIL) | (v[0] == cur))) | is_write \
        | (f == F_NOOP)
    new = jnp.where(is_write, v[0], cur)
    return ok, state.at[0].set(new)


def _mutex_step(state, f, v):
    # reference model.clj:42-56: acquire fails when held, release when not.
    locked = state[0]
    is_acq = f == F_ACQUIRE
    is_rel = f == F_RELEASE
    ok = (is_acq & (locked == 0)) | (is_rel & (locked == 1)) | (f == F_NOOP)
    new = jnp.where(is_acq, jnp.int32(1),
                    jnp.where(is_rel, jnp.int32(0), locked))
    return ok, state.at[0].set(new)


def cas_register_kernel(initial: int = int(NIL)) -> KernelModel:
    return KernelModel("cas-register", 1,
                       lambda: np.array([initial], np.int32),
                       _cas_register_step)


def register_kernel(initial: int = int(NIL)) -> KernelModel:
    return KernelModel("register", 1,
                       lambda: np.array([initial], np.int32),
                       _register_step)


def mutex_kernel() -> KernelModel:
    return KernelModel("mutex", 1,
                       lambda: np.array([0], np.int32),
                       _mutex_step)


def kernel_for(model) -> KernelModel:
    """Map a Python model instance (jepsen_tpu.models) to its device kernel,
    carrying the instance's current state. Register values still pass
    through value interning in :mod:`jepsen_tpu.lin.prepare` (which owns
    the intern table and overrides init_state with the interned id)."""
    from jepsen_tpu import models as m

    if isinstance(model, m.CASRegister):
        return cas_register_kernel()
    if isinstance(model, m.Register):
        return register_kernel()
    if isinstance(model, m.Mutex):
        kern = mutex_kernel()
        if model.locked:
            return KernelModel(kern.name, kern.state_width,
                               lambda: np.array([1], np.int32), kern.step)
        return kern
    raise ValueError(
        f"no device kernel for model {type(model).__name__}; "
        "device linearizability supports register/cas-register/mutex "
        "(use the CPU checker for other models)")
