"""No-cluster test fixtures.

The analogue of the reference's `jepsen/src/jepsen/tests.clj` (12-56):
``noop_test`` — a base test map that does nothing, and an in-memory
atom-backed DB + client (used by the reference's `core_test.clj`
basic-cas-test :18-28) so the full runner can execute with zero
infrastructure: the dummy control transport records commands instead of
SSHing, and the client applies ops against a lock-guarded in-process
register.
"""

from __future__ import annotations

import random
import threading
import time

from jepsen_tpu import checker as checker_ns
from jepsen_tpu import client as client_ns
from jepsen_tpu.history import Op


def noop_test(**overrides) -> dict:
    """A test map that does nothing (tests.clj:12-24)."""
    test = {
        "name": None,               # no persistence by default
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "transport": "dummy",
        "concurrency": 5,
        "generator": None,
        "checker": checker_ns.unbridled_optimism(),
    }
    test.update(overrides)
    return test


class AtomRegister:
    """A lock-guarded in-memory register standing in for a real database
    (the reference's atom-db, core_test.clj)."""

    def __init__(self, value=None):
        self.value = value
        self.lock = threading.Lock()

    def read(self):
        with self.lock:
            return self.value

    def write(self, v):
        with self.lock:
            self.value = v
            return True

    def cas(self, cur, new) -> bool:
        with self.lock:
            if self.value == cur:
                self.value = new
                return True
            return False


class AtomClient(client_ns.Client):
    """Client applying read/write/cas against a shared AtomRegister
    (the reference's atom-client, core_test.clj basic-cas-test)."""

    def __init__(self, register: AtomRegister, latency: float = 0.0):
        self.register = register
        self.latency = latency

    def open(self, test, node):
        return AtomClient(self.register, self.latency)

    def invoke(self, test, op: Op) -> Op:
        if self.latency:
            time.sleep(random.uniform(0, self.latency))
        if op.f == "read":
            return op.replace(type="ok", value=self.register.read())
        if op.f == "write":
            self.register.write(op.value)
            return op.replace(type="ok")
        if op.f == "cas":
            cur, new = op.value
            ok = self.register.cas(cur, new)
            return op.replace(type="ok" if ok else "fail")
        raise ValueError(f"unknown op {op.f!r}")


class CrashyClient(client_ns.Client):
    """Always raises from invoke — exercises worker re-incarnation
    (the reference's worker-recovery-test, core_test.clj:86-101)."""

    def invoke(self, test, op):
        raise RuntimeError("kaboom")
