"""Results web browser.

Re-design of `jepsen/src/jepsen/web.clj` (320 LoC): an http server over
the ``store/`` directory — a home table of runs with valid?-colored rows
(web.clj:116-128), a directory browser with text/image previews
(web.clj:194-229), and zip downloads of whole runs (web.clj:250-271).
Python's http.server replaces http-kit/ring/hiccup.

``/service`` renders the checker daemon's latest stats snapshot (the
daemon writes it to ``JEPSEN_TPU_SERVICE_STATS`` on a cadence and at
shutdown) — queue depths, batch occupancy, verdict counters, latency
percentiles — so the browser shows the serving side next to the runs
it decided, without the web process holding a wire connection to the
daemon.
"""

from __future__ import annotations

import html as _html
import io
import json
import logging
import os
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import quote, unquote

log = logging.getLogger("jepsen.web")

VALID_COLORS = {True: "#ADF6B0", False: "#F6AEAD", "unknown": "#F3F6AD"}


def _run_rows(base: Path) -> list[dict]:
    """All runs, newest first, with their validity (web.clj:47-67
    fast-tests reads each run's results)."""
    rows = []
    if not base.is_dir():
        return rows
    for name in sorted(os.listdir(base)):
        d = base / name
        if name == "latest" or not d.is_dir():
            continue
        for ts in sorted(os.listdir(d), reverse=True):
            run = d / ts
            if ts == "latest" or not run.is_dir():
                continue
            valid = None
            results = run / "results.json"
            if results.exists():
                try:
                    valid = json.loads(results.read_text()).get("valid?")
                except (ValueError, OSError):
                    valid = "unknown"
            rows.append({"name": name, "ts": ts, "valid": valid,
                         "path": f"{name}/{ts}"})
    rows.sort(key=lambda r: r["ts"], reverse=True)
    return rows


def home_html(base: Path) -> str:
    rows = []
    for r in _run_rows(base):
        color = VALID_COLORS.get(r["valid"], "#FFFFFF")
        rows.append(
            f'<tr style="background:{color}">'
            f'<td><a href="/files/{quote(r["path"])}/">'
            f'{_html.escape(r["name"])}</a></td>'
            f'<td><a href="/files/{quote(r["path"])}/">'
            f'{_html.escape(r["ts"])}</a></td>'
            f'<td>{_html.escape(str(r["valid"]))}</td>'
            f'<td><a href="/zip/{quote(r["path"])}">zip</a></td></tr>')
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>jepsen-tpu</title><style>"
            "body{font-family:sans-serif} table{border-collapse:collapse}"
            "td,th{padding:4px 12px;border:1px solid #ccc}"
            "</style></head><body><h1>jepsen-tpu results</h1>"
            '<p><a href="/service">checker service stats</a> · '
            '<a href="/txn">txn anomaly panel</a></p>'
            "<table><tr><th>test</th><th>run</th><th>valid?</th>"
            "<th>download</th></tr>" + "".join(rows) +
            "</table></body></html>")


def dir_html(base: Path, rel: str) -> str:
    d = base / rel
    entries = []
    for name in sorted(os.listdir(d)):
        p = d / name
        href = f"/files/{quote(rel)}/{quote(name)}" + \
            ("/" if p.is_dir() else "")
        preview = ""
        if p.suffix in (".png", ".svg", ".jpg"):
            preview = (f'<br><a href="{href}">'
                       f'<img src="{href}" style="max-width:600px"></a>')
        entries.append(f'<li><a href="{href}">{_html.escape(name)}</a>'
                       f"{preview}</li>")
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'></head>"
            f"<body><h2>{_html.escape(rel)}</h2>"
            '<p><a href="/">home</a></p><ul>' + "".join(entries) +
            "</ul></body></html>")


def service_html(stats_file: str | None = None) -> str:
    """The /service page: the checker daemon's last stats snapshot
    rendered as tables (scalars, then the per-bin dicts), with the raw
    JSON below for anything a table flattens badly."""
    from jepsen_tpu.service import daemon as service_daemon

    path = stats_file or service_daemon.stats_path()
    head = ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>checker service</title><style>"
            "body{font-family:sans-serif} table{border-collapse:collapse;"
            "margin-bottom:1em} td,th{padding:3px 10px;"
            "border:1px solid #ccc} th{text-align:left}"
            "</style></head><body><h1>checker service</h1>"
            '<p><a href="/">home</a></p>')
    try:
        with open(path) as fh:
            snap = json.load(fh)
    except (OSError, ValueError) as e:
        return (head + f"<p>no stats snapshot at "
                f"<code>{_html.escape(str(path))}</code> "
                f"({_html.escape(str(e))}) — is the daemon running "
                f"(<code>cli.py serve-checker</code>)?</p>"
                "</body></html>")

    def table(title, items):
        rows = "".join(
            f"<tr><th>{_html.escape(str(k))}</th>"
            f"<td>{_html.escape(str(v))}</td></tr>"
            for k, v in items)
        return f"<h2>{_html.escape(title)}</h2><table>{rows}</table>"

    scalars = sorted((k, v) for k, v in snap.items()
                     if not isinstance(v, (dict, list)))
    parts = [head, table("counters & gauges", scalars)]
    for k in sorted(k for k, v in snap.items() if isinstance(v, dict)):
        if snap[k]:
            parts.append(table(k, sorted(snap[k].items())))
    parts.append("<h2>raw</h2><pre>"
                 + _html.escape(json.dumps(snap, indent=1,
                                           sort_keys=True))
                 + "</pre></body></html>")
    return "".join(parts)


def txn_html(stats_file: str | None = None) -> str:
    """The /txn anomaly panel: the txn checker's last snapshot
    (written by jepsen_tpu.txn.device on every check to
    ``JEPSEN_TPU_TXN_STATS``) — verdict, anomaly counts by Adya class,
    dependency-edge counts, device tier stats — so the browser shows
    the transactional side next to the runs it decided."""
    # txn.device.stats_path() without the import (pulling the device
    # module would drag jax into the web process).
    path = stats_file or os.environ.get(
        "JEPSEN_TPU_TXN_STATS",
        os.path.join(".jax_cache", "txn_stats.json"))
    head = ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>txn anomalies</title><style>"
            "body{font-family:sans-serif} table{border-collapse:collapse;"
            "margin-bottom:1em} td,th{padding:3px 10px;"
            "border:1px solid #ccc} th{text-align:left}"
            "</style></head><body><h1>txn anomaly checker</h1>"
            '<p><a href="/">home</a></p>')
    try:
        with open(path) as fh:
            snap = json.load(fh)
    except (OSError, ValueError) as e:
        return (head + f"<p>no txn snapshot at "
                f"<code>{_html.escape(str(path))}</code> "
                f"({_html.escape(str(e))}) — run a txn check "
                f"(<code>make txn-smoke</code>)?</p></body></html>")

    color = VALID_COLORS.get(snap.get("verdict"), "#FFFFFF")
    parts = [head,
             f'<p>verdict: <span style="background:{color};'
             f'padding:2px 8px">'
             f"{_html.escape(str(snap.get('verdict')))}</span> "
             f"({_html.escape(str(snap.get('consistency', '?')))}, "
             f"updated {_html.escape(str(snap.get('updated', '?')))})"
             "</p>"]

    def table(title, items):
        rows = "".join(
            f"<tr><th>{_html.escape(str(k))}</th>"
            f"<td>{_html.escape(str(v))}</td></tr>"
            for k, v in items)
        return f"<h2>{_html.escape(title)}</h2><table>{rows}</table>"

    counts = snap.get("anomaly_counts") or {}
    parts.append(table("anomalies",
                       sorted(counts.items()) or [("none found", "-")]))
    for key, title in (("edge_counts", "dependency edges"),
                       ("graph", "graph"), ("device", "device")):
        if isinstance(snap.get(key), dict) and snap[key]:
            parts.append(table(
                title, sorted((k, v) for k, v in snap[key].items()
                              if not isinstance(v, (dict, list)))))
    parts.append("<h2>raw</h2><pre>"
                 + _html.escape(json.dumps(snap, indent=1,
                                           sort_keys=True, default=str))
                 + "</pre></body></html>")
    return "".join(parts)


def zip_run(base: Path, rel: str) -> bytes:
    """Zip a run directory in memory (web.clj:250-271 streams; runs are
    small enough to buffer)."""
    buf = io.BytesIO()
    root = base / rel
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for dirpath, _dirs, files in os.walk(root):
            for f in files:
                full = Path(dirpath) / f
                z.write(full, arcname=str(full.relative_to(base)))
    return buf.getvalue()


class _Handler(BaseHTTPRequestHandler):
    base: Path = Path("store")
    stats_file: str | None = None   # None -> the daemon's default path
    txn_stats_file: str | None = None   # None -> txn.device default

    def log_message(self, fmt, *args):  # route through logging
        log.debug(fmt, *args)

    def _send(self, code: int, body: bytes, ctype="text/html"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _safe_rel(self, rel: str) -> str | None:
        """Reject path traversal out of the store dir (resolved-path
        containment, not a string prefix — /srv/store-secrets must not
        pass for base /srv/store)."""
        target = (self.base / rel).resolve()
        if not target.is_relative_to(self.base.resolve()):
            return None
        return rel

    def do_GET(self):  # noqa: N802 - http.server API
        path = unquote(self.path)
        try:
            if path == "/" or path == "":
                self._send(200, home_html(self.base).encode())
            elif path == "/service":
                self._send(200,
                           service_html(self.stats_file).encode())
            elif path == "/txn":
                self._send(200, txn_html(self.txn_stats_file).encode())
            elif path.startswith("/zip/"):
                rel = self._safe_rel(path[len("/zip/"):].strip("/"))
                if rel is None:
                    return self._send(403, b"forbidden")
                data = zip_run(self.base, rel)
                self.send_response(200)
                self.send_header("Content-Type", "application/zip")
                self.send_header(
                    "Content-Disposition",
                    f'attachment; filename="{rel.replace("/", "_")}.zip"')
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif path.startswith("/files/"):
                rel = self._safe_rel(path[len("/files/"):].strip("/"))
                if rel is None:
                    return self._send(403, b"forbidden")
                target = self.base / rel
                if target.is_dir():
                    self._send(200, dir_html(self.base, rel).encode())
                elif target.is_file():
                    ctype = {"": "text/plain", ".txt": "text/plain",
                             ".log": "text/plain", ".json": "application/json",
                             ".jsonl": "text/plain", ".html": "text/html",
                             ".png": "image/png", ".svg": "image/svg+xml",
                             }.get(target.suffix, "application/octet-stream")
                    self._send(200, target.read_bytes(), ctype)
                else:
                    self._send(404, b"not found")
            else:
                self._send(404, b"not found")
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001
            log.warning("web error on %s: %s", path, e)
            try:
                self._send(500, str(e).encode())
            except Exception:  # noqa: BLE001
                pass


def make_server(host="0.0.0.0", port=8080, base="store",
                stats_file: str | None = None,
                txn_stats_file: str | None = None) -> ThreadingHTTPServer:
    handler = type("Handler", (_Handler,),
                   {"base": Path(base), "stats_file": stats_file,
                    "txn_stats_file": txn_stats_file})
    return ThreadingHTTPServer((host, port), handler)


def serve(host="0.0.0.0", port=8080, base="store") -> None:
    """Run the server until interrupted (web.clj:315-320)."""
    srv = make_server(host, port, base)
    log.info("serving %s on http://%s:%d/", base, host, port)
    print(f"serving {base} on http://{host}:{port}/")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.shutdown()
