"""Results web browser.

Re-design of `jepsen/src/jepsen/web.clj` (320 LoC): an http server over
the ``store/`` directory — a home table of runs with valid?-colored rows
(web.clj:116-128), a directory browser with text/image previews
(web.clj:194-229), and zip downloads of whole runs (web.clj:250-271).
Python's http.server replaces http-kit/ring/hiccup.

``/service`` renders the checker daemon's latest stats snapshot (the
daemon writes it to ``JEPSEN_TPU_SERVICE_STATS`` on a cadence and at
shutdown) — queue depths, batch occupancy, verdict counters, latency
percentiles — so the browser shows the serving side next to the runs
it decided, without the web process holding a wire connection to the
daemon.
"""

from __future__ import annotations

import html as _html
import io
import json
import logging
import os
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import quote, unquote

log = logging.getLogger("jepsen.web")

# THE shared snapshot loader (jepsen_tpu.obs — jax-free at import):
# /service, /txn, /run and the CLI's service-stats / host-stats all
# read snapshot files through it instead of hand-rolled open/load.
from jepsen_tpu.obs.metrics import (  # noqa: E402
    load_json_snapshot as _load_snapshot,
)

# Run-directory evidence the home/dir pages link when present — ONE
# definition (and ONE lookup, subdirectory-aware) shared with the
# store flow that writes it (store.write_run_artifacts), so the link
# list cannot drift from what runs actually contain.
from jepsen_tpu.store import (  # noqa: E402
    RUN_ARTIFACTS,
    find_artifacts as _find_artifacts,
)

VALID_COLORS = {True: "#ADF6B0", False: "#F6AEAD", "unknown": "#F3F6AD"}


def _run_rows(base: Path) -> list[dict]:
    """All runs, newest first, with their validity (web.clj:47-67
    fast-tests reads each run's results)."""
    rows = []
    if not base.is_dir():
        return rows
    for name in sorted(os.listdir(base)):
        d = base / name
        if name == "latest" or not d.is_dir():
            continue
        for ts in sorted(os.listdir(d), reverse=True):
            run = d / ts
            if ts == "latest" or not run.is_dir():
                continue
            valid = None
            results = run / "results.json"
            if results.exists():
                try:
                    valid = json.loads(results.read_text()).get("valid?")
                except (ValueError, OSError):
                    valid = "unknown"
            found = _find_artifacts(run)
            arts = [(a, found[a].relative_to(run).as_posix())
                    for a in RUN_ARTIFACTS if a in found]
            rows.append({"name": name, "ts": ts, "valid": valid,
                         "path": f"{name}/{ts}",
                         "artifacts": arts})
    rows.sort(key=lambda r: r["ts"], reverse=True)
    return rows


def home_html(base: Path) -> str:
    rows = []
    for r in _run_rows(base):
        color = VALID_COLORS.get(r["valid"], "#FFFFFF")
        # One-click evidence links (the perf-ledger satellite,
        # doc/observability.md): a run's latency/rate/timeline
        # artifacts next to its row.
        evidence = " · ".join(
            f'<a href="/files/{quote(r["path"])}/{quote(rel)}">'
            f"{_html.escape(a.split('.')[0].replace('latency-', 'lat-'))}"
            f"</a>" for a, rel in r["artifacts"]) or "-"
        rows.append(
            f'<tr style="background:{color}">'
            f'<td><a href="/files/{quote(r["path"])}/">'
            f'{_html.escape(r["name"])}</a></td>'
            f'<td><a href="/files/{quote(r["path"])}/">'
            f'{_html.escape(r["ts"])}</a></td>'
            f'<td>{_html.escape(str(r["valid"]))}</td>'
            f"<td>{evidence}</td>"
            f'<td><a href="/zip/{quote(r["path"])}">zip</a></td></tr>')
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>jepsen-tpu</title><style>"
            "body{font-family:sans-serif} table{border-collapse:collapse}"
            "td,th{padding:4px 12px;border:1px solid #ccc}"
            "</style></head><body><h1>jepsen-tpu results</h1>"
            '<p><a href="/service">checker service stats</a> · '
            '<a href="/txn">txn anomaly panel</a> · '
            '<a href="/run">run telemetry</a> · '
            '<a href="/perf">perf ledger</a></p>'
            "<table><tr><th>test</th><th>run</th><th>valid?</th>"
            "<th>evidence</th><th>download</th></tr>" + "".join(rows) +
            "</table></body></html>")


def dir_html(base: Path, rel: str) -> str:
    d = base / rel
    entries = []
    for name in sorted(os.listdir(d)):
        p = d / name
        href = f"/files/{quote(rel)}/{quote(name)}" + \
            ("/" if p.is_dir() else "")
        preview = ""
        if p.suffix in (".png", ".svg", ".jpg"):
            preview = (f'<br><a href="{href}">'
                       f'<img src="{href}" style="max-width:600px"></a>')
        entries.append(f'<li><a href="{href}">{_html.escape(name)}</a>'
                       f"{preview}</li>")
    # Evidence shortcuts: a RUN dir's latency/rate/timeline artifacts
    # one click from the top (wherever a checker placed them), next
    # to the perf-ledger trend page. Only for run directories — for
    # the store root or a test-name dir holding many runs, the walk
    # would present some arbitrary run's files as "evidence".
    ev = []
    if any((d / marker).exists()
           for marker in ("results.json", "test.json",
                          "history.jsonl")):
        found = _find_artifacts(d)
        for a in RUN_ARTIFACTS:
            if a in found:
                rel_a = found[a].relative_to(d).as_posix()
                ev.append(
                    f'<a href="/files/{quote(rel)}/{quote(rel_a)}">'
                    f"{_html.escape(a)}</a>")
    evidence = " · ".join(ev)
    ev_line = f"<p>evidence: {evidence}</p>" if evidence else ""
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'></head>"
            f"<body><h2>{_html.escape(rel)}</h2>"
            '<p><a href="/">home</a> · <a href="/perf">perf ledger</a>'
            "</p>" + ev_line + "<ul>" + "".join(entries) +
            "</ul></body></html>")


def service_html(stats_file: str | None = None) -> str:
    """The /service page: the checker daemon's last stats snapshot
    rendered as tables (scalars, then the per-bin dicts), with the raw
    JSON below for anything a table flattens badly."""
    from jepsen_tpu.service import daemon as service_daemon

    path = stats_file or service_daemon.stats_path()
    head = ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>checker service</title><style>"
            "body{font-family:sans-serif} table{border-collapse:collapse;"
            "margin-bottom:1em} td,th{padding:3px 10px;"
            "border:1px solid #ccc} th{text-align:left}"
            "</style></head><body><h1>checker service</h1>"
            '<p><a href="/">home</a></p>')
    snap, err = _load_snapshot(path)
    if snap is None:
        return (head + f"<p>no stats snapshot at "
                f"<code>{_html.escape(str(path))}</code> "
                f"({_html.escape(str(err))}) — is the daemon running "
                f"(<code>cli.py serve-checker</code>)?</p>"
                "</body></html>")

    def table(title, items):
        rows = "".join(
            f"<tr><th>{_html.escape(str(k))}</th>"
            f"<td>{_html.escape(str(v))}</td></tr>"
            for k, v in items)
        return f"<h2>{_html.escape(title)}</h2><table>{rows}</table>"

    scalars = sorted((k, v) for k, v in snap.items()
                     if not isinstance(v, (dict, list)))
    # Fleet health up front (doc/service.md § Fleet): pool size and
    # journal depth are the two numbers that say whether the daemon is
    # keeping its crash-recovery promises right now.
    fleet = []
    if snap.get("workers") is not None:
        fleet.append(f"workers {snap.get('workers')} "
                     f"({snap.get('workers_busy', 0)} busy, "
                     f"{snap.get('worker_deaths', 0)} deaths, "
                     f"{snap.get('worker_respawns', 0)} respawns)")
    if snap.get("journal_path"):
        fleet.append(f"journal depth {snap.get('journal_depth', 0)} "
                     f"unsettled, {snap.get('journal_settles', 0)} "
                     f"settled, {snap.get('journal_replays', 0)} "
                     f"replayed")
    parts = [head]
    if fleet:
        parts.append("<p><b>fleet:</b> "
                     + _html.escape(" · ".join(fleet)) + "</p>")
    # Pack meter up front (doc/service.md § Device packing): total
    # pack wall, the mode that served the last pack, and the device
    # packer's dispatch/lane/fallback counts — the admission-offload
    # surface next to the per-bin ``bin_pack_s`` table below.
    if snap.get("pack_seconds") is not None:
        pk = (f"{snap.get('pack_seconds')} s over "
              f"{snap.get('pack_calls', 0)} packs "
              f"(mode {snap.get('pack_mode')}")
        if snap.get("pack_dev_packs"):
            pk += (f"; device: {snap.get('pack_dev_packs')} dispatches"
                   f" / {snap.get('pack_dev_lanes')} lanes in "
                   f"{snap.get('pack_dev_seconds')} s, "
                   f"{snap.get('pack_dev_fallbacks', 0)} host "
                   f"fallbacks")
        pk += ")"
        parts.append("<p><b>pack:</b> " + _html.escape(pk) + "</p>")
    parts.append(table("counters & gauges", scalars))
    # Placement block (doc/service.md § Placement): one row per worker
    # SLOT — device, queue depth, busy-seconds, item/compile counts —
    # the per-device surface the generic dict tables flatten badly.
    placement = snap.get("placement") or {}
    if placement.get("workers"):
        cols = ("wid", "slot", "device", "queue_depth", "busy",
                "items", "busy_s", "compiles")
        rows = ["<tr>" + "".join(f"<th>{c}</th>" for c in cols)
                + "</tr>"]
        for w in placement["workers"]:
            rows.append("<tr>" + "".join(
                f"<td>{_html.escape(str(w.get(c)))}</td>"
                for c in cols) + "</tr>")
        summary = (f"{len(placement.get('homes') or {})} bin homes · "
                   f"{placement.get('placed', 0)} placed "
                   f"({placement.get('homed', 0)} home, "
                   f"{placement.get('spills', 0)} spills, "
                   f"{placement.get('re_homes', 0)} re-homes; "
                   f"spill depth {placement.get('spill_depth')})")
        lost = placement.get("lost_devices")
        if lost:
            summary += f" · LOST devices {lost}"
        parts.append("<h2>placement</h2><p>"
                     + _html.escape(summary) + "</p><table>"
                     + "".join(rows) + "</table>")
    for k in sorted(k for k, v in snap.items() if isinstance(v, dict)):
        if snap[k] and k != "placement":
            parts.append(table(k, sorted(snap[k].items())))
    parts.append("<h2>raw</h2><pre>"
                 + _html.escape(json.dumps(snap, indent=1,
                                           sort_keys=True))
                 + "</pre></body></html>")
    return "".join(parts)


def txn_html(stats_file: str | None = None) -> str:
    """The /txn anomaly panel: the txn checker's last snapshot
    (written by jepsen_tpu.txn.device on every check to
    ``JEPSEN_TPU_TXN_STATS``) — verdict, anomaly counts by Adya class,
    dependency-edge counts, device tier stats — so the browser shows
    the transactional side next to the runs it decided."""
    # txn.device.stats_path() without the import (pulling the device
    # module would drag jax into the web process).
    path = stats_file or os.environ.get(
        "JEPSEN_TPU_TXN_STATS",
        os.path.join(".jax_cache", "txn_stats.json"))
    head = ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>txn anomalies</title><style>"
            "body{font-family:sans-serif} table{border-collapse:collapse;"
            "margin-bottom:1em} td,th{padding:3px 10px;"
            "border:1px solid #ccc} th{text-align:left}"
            "</style></head><body><h1>txn anomaly checker</h1>"
            '<p><a href="/">home</a></p>')
    snap, err = _load_snapshot(path)
    if snap is None:
        return (head + f"<p>no txn snapshot at "
                f"<code>{_html.escape(str(path))}</code> "
                f"({_html.escape(str(err))}) — run a txn check "
                f"(<code>make txn-smoke</code>)?</p></body></html>")

    color = VALID_COLORS.get(snap.get("verdict"), "#FFFFFF")
    parts = [head,
             f'<p>verdict: <span style="background:{color};'
             f'padding:2px 8px">'
             f"{_html.escape(str(snap.get('verdict')))}</span> "
             f"({_html.escape(str(snap.get('consistency', '?')))}, "
             f"updated {_html.escape(str(snap.get('updated', '?')))})"
             "</p>"]

    def table(title, items):
        rows = "".join(
            f"<tr><th>{_html.escape(str(k))}</th>"
            f"<td>{_html.escape(str(v))}</td></tr>"
            for k, v in items)
        return f"<h2>{_html.escape(title)}</h2><table>{rows}</table>"

    counts = snap.get("anomaly_counts") or {}
    parts.append(table("anomalies",
                       sorted(counts.items()) or [("none found", "-")]))
    for key, title in (("edge_counts", "dependency edges"),
                       ("graph", "graph"), ("device", "device")):
        if isinstance(snap.get(key), dict) and snap[key]:
            parts.append(table(
                title, sorted((k, v) for k, v in snap[key].items()
                              if not isinstance(v, (dict, list)))))
    parts.append("<h2>raw</h2><pre>"
                 + _html.escape(json.dumps(snap, indent=1,
                                           sort_keys=True, default=str))
                 + "</pre></body></html>")
    return "".join(parts)


def _spark_svg(pts: list[tuple[float, float]], label: str = "",
               width=600, height=60, color="#4078c0") -> str:
    """Inline SVG sparkline over (x, y) points (no JS, no external
    assets — the page must render from a file). Shared by the /run
    frontier sparkline and the /perf wall/dispatch trend rows."""
    if len(pts) < 2:
        return "<span>(not enough samples)</span>"
    t0, t1 = pts[0][0], pts[-1][0]
    vmax = max(v for _, v in pts) or 1
    dt = (t1 - t0) or 1
    path = " ".join(
        f"{'M' if i == 0 else 'L'}"
        f"{(t - t0) / dt * (width - 4) + 2:.1f},"
        f"{height - 2 - v / vmax * (height - 14):.1f}"
        for i, (t, v) in enumerate(pts))
    text = (f'<text x="4" y="12" font-size="10">'
            f"{_html.escape(label)}</text>" if label else "")
    return (f'<svg width="{width}" height="{height}" '
            f'style="border:1px solid #ccc">'
            f'<path d="{path}" fill="none" stroke="{color}" '
            f'stroke-width="1.5"/>{text}</svg>')


def _sparkline_svg(samples: list, width=600, height=60) -> str:
    """The /run frontier sparkline: frontier size over elapsed
    seconds, through the shared :func:`_spark_svg` helper."""
    pts = [(s[0], s[2]) for s in samples
           if isinstance(s, (list, tuple)) and len(s) >= 3
           and s[2] is not None]
    if len(pts) < 2:
        return "<p>(not enough samples for a sparkline yet)</p>"
    vmax = max(v for _, v in pts) or 1
    return _spark_svg(pts, label=f"frontier (max {vmax})",
                      width=width, height=height)


def run_html(snapshot_file: str | None = None) -> str:
    """The /run live-telemetry page: the obs registry's run-telemetry
    snapshot (written by the engines every JEPSEN_TPU_OBS_EVERY_S at
    committed row boundaries) rendered as progress gauges (row, ETA,
    rows/s), the frontier-size sparkline, the watchdog/quarantine
    event feed, and every registered stats view — so a wedged config-5
    run is diagnosable from a browser without attaching a debugger."""
    from jepsen_tpu.obs import metrics as obs_metrics

    path = snapshot_file or obs_metrics.snapshot_path()
    head = ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<meta http-equiv='refresh' content='5'>"
            "<title>run telemetry</title><style>"
            "body{font-family:sans-serif} table{border-collapse:collapse;"
            "margin-bottom:1em} td,th{padding:3px 10px;"
            "border:1px solid #ccc} th{text-align:left}"
            "</style></head><body><h1>run telemetry</h1>"
            '<p><a href="/">home</a> (auto-refreshes every 5 s)</p>')
    snap, err = _load_snapshot(path)
    if snap is None:
        return (head + f"<p>no run-telemetry snapshot at "
                f"<code>{_html.escape(str(path))}</code> "
                f"({_html.escape(str(err))}) — is an engine check "
                f"running (doc/observability.md)?</p></body></html>")

    def table(title, items):
        rows = "".join(
            f"<tr><th>{_html.escape(str(k))}</th>"
            f"<td>{_html.escape(str(v))}</td></tr>"
            for k, v in items)
        return f"<h2>{_html.escape(title)}</h2><table>{rows}</table>"

    run = snap.get("run") or {}
    parts = [head]
    row, total = run.get("row"), run.get("total_rows")
    bar = ""
    if row is not None and total:
        pct = min(100.0, 100.0 * row / total)
        bar = (f'<div style="width:600px;border:1px solid #ccc">'
               f'<div style="width:{pct:.1f}%;background:#ADF6B0">'
               f"&nbsp;{pct:.1f}%</div></div>")
    parts.append(
        f"<p>run <b>{_html.escape(str(run.get('run', '?')))}</b> · "
        f"updated {_html.escape(str(snap.get('updated', '?')))} · "
        f"pid {_html.escape(str(snap.get('pid', '?')))}</p>" + bar)
    gauges = [(k, v) for k, v in sorted(run.items()) if k != "run"]
    gauges += [(k, snap[k]) for k in ("xla_compiles", "xla_compile_s",
                                      "xla_cache_hits")
               if snap.get(k) is not None]
    parts.append(table("progress", gauges))
    stream = (snap.get("views") or {}).get("stream") or {}
    if stream:
        # Streaming checker telemetry (doc/streaming.md): the ingest-
        # vs-checked lag gauge — how far the live checker trails the
        # producing run — plus the abort latch, loudly.
        settled = stream.get("rows_settled") or 0
        checked = stream.get("rows_checked") or 0
        lag_bar = ""
        if settled:
            pct = min(100.0, 100.0 * checked / settled)
            lag_bar = (
                f'<div style="width:600px;border:1px solid #ccc">'
                f'<div style="width:{pct:.1f}%;background:#B0D8F6">'
                f"&nbsp;checked {checked} / settled {settled} "
                f"(lag {stream.get('lag_rows', settled - checked)} "
                f"rows)</div></div>")
        banner = ""
        if stream.get("aborted"):
            banner = (
                '<p style="background:#F6B0B0;padding:4px">'
                "<b>stream ABORTED</b>: invalid increment at row "
                f"{_html.escape(str(stream.get('aborted_row')))}</p>")
        parts.append("<h2>stream checker</h2>" + banner + lag_bar)
        parts.append(table("stream", sorted(
            (k, v) for k, v in stream.items()
            if not isinstance(v, (dict, list)))))
    parts.append("<h2>frontier</h2>"
                 + _sparkline_svg(snap.get("samples") or []))
    events = snap.get("events") or []
    if events:
        rows = "".join(
            f"<tr><td>{_html.escape(str(e.get('t')))}</td>"
            f"<td>{_html.escape(str(e.get('kind')))}</td>"
            f"<td>{_html.escape(str({k: v for k, v in e.items() if k not in ('t', 'kind')}))}</td></tr>"
            for e in events[-24:])
        parts.append("<h2>events (watchdog / quarantine)</h2>"
                     "<table><tr><th>time</th><th>kind</th>"
                     "<th>detail</th></tr>" + rows + "</table>")
    for name in sorted(snap.get("views") or {}):
        if name == "stream":
            continue   # rendered above with its lag gauge
        view = snap["views"][name] or {}
        parts.append(table(
            name, sorted((k, v) for k, v in view.items()
                         if not isinstance(v, (dict, list)))))
    parts.append("<h2>raw</h2><pre>"
                 + _html.escape(json.dumps(snap, indent=1,
                                           sort_keys=True,
                                           default=str))
                 + "</pre></body></html>")
    return "".join(parts)


def perf_html(ledger_file: str | None = None) -> str:
    """The /perf trend page: the cross-run perf ledger
    (jepsen_tpu.obs.ledger, doc/observability.md § Perf ledger) as one
    row per (probe, platform) — run count, wall-seconds sparkline,
    dispatches/episode sparkline, trailing median, verdict history
    (colored chips), last git sha — so a perf regression or verdict
    flip reads off a browser the way `cli.py perf report` prints it."""
    from jepsen_tpu.obs import ledger as ledger_mod

    path = ledger_file or ledger_mod.ledger_path()
    head = ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>perf ledger</title><style>"
            "body{font-family:sans-serif} table{border-collapse:collapse;"
            "margin-bottom:1em} td,th{padding:3px 10px;"
            "border:1px solid #ccc} th{text-align:left}"
            ".chip{display:inline-block;width:12px;height:12px;"
            "margin-right:1px;border:1px solid #999}"
            "</style></head><body><h1>perf ledger</h1>"
            '<p><a href="/">home</a> · <a href="/run">run telemetry</a>'
            "</p>")
    if path is None:
        # Recording disabled: telling the operator to run a smoke
        # would be wrong guidance — nothing can produce records.
        return (head + "<p>perf ledger disabled "
                "(<code>JEPSEN_TPU_PERF_LEDGER=0</code>) — unset it "
                "(doc/env.md) to start recording</p></body></html>")
    records = ledger_mod.load(path)
    if not records:
        return (head + f"<p>no perf-ledger records at "
                f"<code>{_html.escape(str(path))}</code> — run a "
                f"bench probe or any <code>make *-smoke</code> "
                f"(doc/observability.md § Perf ledger)</p>"
                "</body></html>")
    by_group: dict[str, list[dict]] = {}
    for r in records:
        by_group.setdefault(ledger_mod.group_key(r), []).append(r)
    rows_html = []
    for key, row in ledger_mod.trend(records).items():
        recs = by_group.get(key, [])
        # Same evidence rule as trend()/gate(): resumed tails and
        # errored runs are excluded, or the sparkline would show a
        # dip the median annotation under it (rightly) ignores.
        walls = [(i, r["wall_s"]) for i, r in enumerate(recs)
                 if isinstance(r.get("wall_s"), (int, float))
                 and ledger_mod.ratio_evidence(r)]
        dpes = [(i, r["dispatches_per_episode"])
                for i, r in enumerate(recs)
                if isinstance(r.get("dispatches_per_episode"),
                              (int, float))
                and ledger_mod.ratio_evidence(r)]
        chips = "".join(
            f'<span class="chip" title="{_html.escape(str(r.get("t")))}'
            f' {_html.escape(str(r.get("verdict")))}" '
            f'style="background:'
            f'{VALID_COLORS.get(r.get("verdict"), "#DDD")}"></span>'
            for r in recs[-16:])
        rows_html.append(
            f"<tr><td><b>{_html.escape(str(row['probe']))}</b><br>"
            f"<small>{_html.escape(str(row['platform']))} · "
            f"git {_html.escape(str(row['last_git']))}</small></td>"
            f"<td>{row['n']}</td>"
            f"<td>{_spark_svg(walls, label='wall s', width=220, height=36)}"
            f"<br><small>last {row['last_wall_s']} s · median "
            f"{row['median_wall_s']} s"
            + (f" · <b>{row['wall_vs_median']}x</b>"
               if row.get("wall_vs_median") else "") + "</small></td>"
            f"<td>{_spark_svg(dpes, label='disp/ep', width=160, height=36, color='#c07840')}"
            f"<br><small>{row['last_dispatches_per_episode'] or '-'}"
            "</small></td>"
            f"<td>{chips}<br><small>{_html.escape(row['verdicts'])}"
            "</small>"
            + (f"<br><small>! {_html.escape(str(row['last_error'])[:60])}"
               f"</small>" if row.get("last_error") else "")
            + (f"<br><small>+{len(row['quarantine_new'])} quarantine"
               f"</small>" if row.get("quarantine_new") else "")
            + "</td></tr>")
    return (head
            + f"<p>{len(records)} record(s) in "
              f"<code>{_html.escape(str(path))}</code></p>"
              "<table><tr><th>probe</th><th>runs</th><th>wall</th>"
              "<th>dispatches/episode</th><th>verdicts</th></tr>"
            + "".join(rows_html) + "</table></body></html>")


def zip_run(base: Path, rel: str) -> bytes:
    """Zip a run directory in memory (web.clj:250-271 streams; runs are
    small enough to buffer)."""
    buf = io.BytesIO()
    root = base / rel
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for dirpath, _dirs, files in os.walk(root):
            for f in files:
                full = Path(dirpath) / f
                z.write(full, arcname=str(full.relative_to(base)))
    return buf.getvalue()


class _Handler(BaseHTTPRequestHandler):
    base: Path = Path("store")
    stats_file: str | None = None   # None -> the daemon's default path
    txn_stats_file: str | None = None   # None -> txn.device default
    run_stats_file: str | None = None   # None -> obs registry default
    perf_ledger_file: str | None = None   # None -> obs ledger default

    def log_message(self, fmt, *args):  # route through logging
        log.debug(fmt, *args)

    def _send(self, code: int, body: bytes, ctype="text/html"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _safe_rel(self, rel: str) -> str | None:
        """Reject path traversal out of the store dir (resolved-path
        containment, not a string prefix — /srv/store-secrets must not
        pass for base /srv/store)."""
        target = (self.base / rel).resolve()
        if not target.is_relative_to(self.base.resolve()):
            return None
        return rel

    def do_GET(self):  # noqa: N802 - http.server API
        path = unquote(self.path)
        try:
            if path == "/" or path == "":
                self._send(200, home_html(self.base).encode())
            elif path == "/service":
                self._send(200,
                           service_html(self.stats_file).encode())
            elif path == "/txn":
                self._send(200, txn_html(self.txn_stats_file).encode())
            elif path == "/run":
                self._send(200, run_html(self.run_stats_file).encode())
            elif path == "/perf":
                self._send(200,
                           perf_html(self.perf_ledger_file).encode())
            elif path.startswith("/zip/"):
                rel = self._safe_rel(path[len("/zip/"):].strip("/"))
                if rel is None:
                    return self._send(403, b"forbidden")
                data = zip_run(self.base, rel)
                self.send_response(200)
                self.send_header("Content-Type", "application/zip")
                self.send_header(
                    "Content-Disposition",
                    f'attachment; filename="{rel.replace("/", "_")}.zip"')
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif path.startswith("/files/"):
                rel = self._safe_rel(path[len("/files/"):].strip("/"))
                if rel is None:
                    return self._send(403, b"forbidden")
                target = self.base / rel
                if target.is_dir():
                    self._send(200, dir_html(self.base, rel).encode())
                elif target.is_file():
                    ctype = {"": "text/plain", ".txt": "text/plain",
                             ".log": "text/plain", ".json": "application/json",
                             ".jsonl": "text/plain", ".html": "text/html",
                             ".png": "image/png", ".svg": "image/svg+xml",
                             }.get(target.suffix, "application/octet-stream")
                    self._send(200, target.read_bytes(), ctype)
                else:
                    self._send(404, b"not found")
            else:
                self._send(404, b"not found")
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001
            log.warning("web error on %s: %s", path, e)
            try:
                self._send(500, str(e).encode())
            except Exception:  # noqa: BLE001
                pass


def make_server(host="0.0.0.0", port=8080, base="store",
                stats_file: str | None = None,
                txn_stats_file: str | None = None,
                run_stats_file: str | None = None,
                perf_ledger_file: str | None = None,
                ) -> ThreadingHTTPServer:
    handler = type("Handler", (_Handler,),
                   {"base": Path(base), "stats_file": stats_file,
                    "txn_stats_file": txn_stats_file,
                    "run_stats_file": run_stats_file,
                    "perf_ledger_file": perf_ledger_file})
    return ThreadingHTTPServer((host, port), handler)


def serve(host="0.0.0.0", port=8080, base="store") -> None:
    """Run the server until interrupted (web.clj:315-320)."""
    srv = make_server(host, port, base)
    log.info("serving %s on http://%s:%d/", base, host, port)
    print(f"serving {base} on http://{host}:{port}/")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.shutdown()
