"""OS provisioning protocol (reference `jepsen/src/jepsen/os.clj`, 14 LoC).

Concrete implementations: :mod:`jepsen_tpu.os_debian` (apt-based, the
reference's os/debian.clj) — others can be added per suite like the
reference's smartos/ubuntu variants.
"""

from __future__ import annotations


class OS:
    def setup(self, test, node) -> None:
        """Prepare the node's operating system (os.clj:5-6)."""

    def teardown(self, test, node) -> None:
        """Remove any changes made (os.clj:7-8)."""


class NoopOS(OS):
    """Does nothing (os.clj:10-14)."""


noop = NoopOS()
