"""ctypes loader for the native C++ helpers in ``native/``.

The reference's native surface is C compiled on demand (the clock-fault
programs, nemesis/time.clj:12-27); ours adds ``history_pack.cc`` — the
O(R x W) packing walk of :mod:`jepsen_tpu.lin.prepare` — built the same
way: from source, on first use, with the toolchain at hand. No native
artifacts are vendored; everything degrades to the Python path.

Set ``JTPU_NO_NATIVE=1`` to force the Python fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_BUILD_DIR = _NATIVE_DIR / "build"
_SRC = _NATIVE_DIR / "history_pack.cc"
_LIB = _BUILD_DIR / "libhistorypack.so"

_lib = None
_load_failed = False


def _build() -> bool:
    """Compile the shared library if missing or stale. Returns success."""
    try:
        if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
            return True
        _BUILD_DIR.mkdir(parents=True, exist_ok=True)
        # Compile to a private temp name, then atomically rename: an
        # interrupted/concurrent build must never leave a corrupt .so
        # that passes the staleness check.
        tmp = _BUILD_DIR / f".libhistorypack.{os.getpid()}.so"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
             "-o", str(tmp), str(_SRC)],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    if os.environ.get("JTPU_NO_NATIVE"):
        _load_failed = True
        return None
    if not _build():
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(str(_LIB))
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.jtpu_pack_events.restype = ctypes.c_int
        lib.jtpu_pack_events.argtypes = [
            ctypes.c_int32,                 # n_ops
            i32p, i32p, i32p, i32p, i32p,   # invoke/return/f/v0/v1
            ctypes.c_int32,                 # nil_value
            ctypes.c_int32,                 # max_window
            ctypes.c_int32,                 # fill_fv
            ctypes.c_int32,                 # R
            i32p, i32p,                     # ret_slot, ret_op
            u8p, i32p, i32p, i32p,          # active, slot_f, slot_v, slot_op
            ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
    except OSError:
        _load_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


class WindowOverflow(Exception):
    """Concurrency window exceeded max_window at history position .pos."""

    def __init__(self, pos: int):
        super().__init__(f"window overflow at history position {pos}")
        self.pos = pos


def pack_events(invoke_pos, return_pos, f_id, v0, v1, *,
                nil_value: int, max_window: int, fill_fv: bool, R: int):
    """Run the native packing walk. Returns
    (ret_slot, ret_op, active, slot_f, slot_v, slot_op, window) with
    output tables pre-filled to the same defaults as the Python walk
    (active False, slot_f 0, slot_v NIL, slot_op -1). None if the native
    library is unavailable (caller falls back); raises WindowOverflow on
    the same condition the Python walk raises UnsupportedHistory."""
    from jepsen_tpu.models.kernels import VALUE_WIDTH

    # The C ABI is fixed at two value words (v0/v1, slot_v[..., 2]); fail
    # loudly rather than silently dropping columns if the kernel constant
    # ever grows.
    assert VALUE_WIDTH == 2, \
        f"native packer supports VALUE_WIDTH == 2, got {VALUE_WIDTH}"
    lib = _load()
    if lib is None:
        return None
    n = len(invoke_pos)
    invoke_pos = np.ascontiguousarray(invoke_pos, np.int32)
    return_pos = np.ascontiguousarray(return_pos, np.int32)
    f_id = np.ascontiguousarray(f_id, np.int32)
    v0 = np.ascontiguousarray(v0, np.int32)
    v1 = np.ascontiguousarray(v1, np.int32)

    ret_slot = np.zeros(R, np.int32)
    ret_op = np.zeros(R, np.int32)
    active = np.zeros((R, max_window), np.uint8)
    slot_f = np.zeros((R, max_window), np.int32)
    slot_v = np.full((R, max_window, 2), nil_value, np.int32)
    slot_op = np.full((R, max_window), -1, np.int32)
    out_w = ctypes.c_int32(0)

    rc = lib.jtpu_pack_events(
        np.int32(n), invoke_pos, return_pos, f_id, v0, v1,
        np.int32(nil_value), np.int32(max_window), np.int32(int(fill_fv)),
        np.int32(R), ret_slot, ret_op,
        active.reshape(-1), slot_f.reshape(-1), slot_v.reshape(-1),
        slot_op.reshape(-1), ctypes.byref(out_w))
    if rc == -1:
        raise WindowOverflow(int(out_w.value))
    if rc != 0:
        return None
    return (ret_slot, ret_op, active.astype(bool), slot_f, slot_v,
            slot_op, int(out_w.value))
