"""Network manipulation: partitions, delay, loss.

Re-design of `jepsen/src/jepsen/net.clj` (109 LoC): the Net protocol
(net.clj:9-20) with the iptables implementation (net.clj:34-75) driving
``iptables`` + ``tc qdisc netem`` over the control plane. The ipfilter
variant for SmartOS-style nodes mirrors net.clj:77-109.
"""

from __future__ import annotations

from jepsen_tpu import control as c


class Net:
    def drop(self, test, src, dest) -> None:
        """Drop traffic from src to dest (net.clj:10-11)."""

    def heal(self, test) -> None:
        """End all traffic drops and restore network (net.clj:12-13)."""

    def slow(self, test, mean_ms: float = 50, sigma_ms: float = 10) -> None:
        """Delay all packets (net.clj:14-15)."""

    def flaky(self, test) -> None:
        """Introduce packet loss (net.clj:16-17)."""

    def fast(self, test) -> None:
        """Remove packet loss and delays (net.clj:18-19)."""


class NoopNet(Net):
    """Does nothing (net.clj:24-32)."""


noop = NoopNet()


class IptablesNet(Net):
    """Default implementation: iptables droprules + tc netem delay/loss
    (net.clj:34-75)."""

    def drop(self, test, src, dest):
        def go():
            with c.su():
                c.exec_("iptables", "-A", "INPUT", "-s", _ip(src),
                        "-j", "DROP", "-w")
        c.on(test, dest, go)

    def heal(self, test):
        def go(test_, node):
            with c.su():
                c.exec_("iptables", "-F", "-w")
                c.exec_("iptables", "-X", "-w")
        c.on_nodes(test, go)

    def slow(self, test, mean_ms: float = 50, sigma_ms: float = 10):
        def go(test_, node):
            with c.su():
                c.exec_("tc", "qdisc", "add", "dev", "eth0", "root",
                        "netem", "delay", f"{mean_ms:g}ms",
                        f"{sigma_ms:g}ms", "distribution", "normal")
        c.on_nodes(test, go)

    def flaky(self, test):
        def go(test_, node):
            with c.su():
                c.exec_("tc", "qdisc", "add", "dev", "eth0", "root",
                        "netem", "loss", "20%", "75%")
        c.on_nodes(test, go)

    def fast(self, test):
        def go(test_, node):
            with c.su():
                c.exec_("tc", "qdisc", "del", "dev", "eth0", "root",
                        may_fail=True)
        c.on_nodes(test, go)


iptables = IptablesNet()


class IpfilterNet(Net):
    """ipfilter-based variant (net.clj:77-109)."""

    def drop(self, test, src, dest):
        def go():
            with c.su():
                c.exec_("echo", f"block in from {_ip(src)} to any",
                        c.Lit("| ipf -f -"))
        c.on(test, dest, go)

    def heal(self, test):
        def go(test_, node):
            with c.su():
                c.exec_("ipf", "-Fa")
        c.on_nodes(test, go)


ipfilter = IpfilterNet()


def _ip(node: str) -> str:
    return node
