"""jepsen_tpu: a TPU-native distributed-systems correctness testing framework.

A brand-new framework with the capabilities of Jepsen (the reference lives at
/root/reference): a harness that provisions real distributed systems over SSH,
drives concurrent client workloads while a nemesis injects faults, records
every operation into a timestamped history, and checks that history against
abstract models — with the expensive linearizability search rebuilt as a
JAX/XLA device kernel (a breadth-first frontier over
(linearized-op-bitset x model-state) configurations) instead of the JVM
Knossos solver.

Layer map (mirrors the reference's, SURVEY.md §1):

- :mod:`jepsen_tpu.history`     — op/history interchange format (core.clj:143-217)
- :mod:`jepsen_tpu.models`      — abstract models (model.clj)
- :mod:`jepsen_tpu.checker`     — history validators (checker.clj)
- :mod:`jepsen_tpu.lin`         — the TPU linearizability kernel (replaces knossos)
- :mod:`jepsen_tpu.generator`   — operation generator DSL (generator.clj)
- :mod:`jepsen_tpu.client`      — client protocol (client.clj)
- :mod:`jepsen_tpu.db`          — DB lifecycle protocol (db.clj)
- :mod:`jepsen_tpu.os_`         — OS provisioning (os.clj)
- :mod:`jepsen_tpu.net`         — network manipulation (net.clj)
- :mod:`jepsen_tpu.nemesis`     — fault injection (nemesis.clj)
- :mod:`jepsen_tpu.control`     — SSH control plane (control.clj)
- :mod:`jepsen_tpu.core`        — test runner (core.clj)
- :mod:`jepsen_tpu.store`       — persistence (store.clj)
- :mod:`jepsen_tpu.cli`         — command line runner (cli.clj)
- :mod:`jepsen_tpu.web`         — results browser (web.clj)
"""

__version__ = "0.1.0"
