"""SmartOS provisioning.

The analogue of `jepsen/src/jepsen/os/smartos.clj` (132 LoC): pkgin-based
package management mirroring the debian module's shape, used by the
reference's mongodb-smartos suite. SmartOS ships ipfilter instead of
iptables, so suites on this OS pair it with
:class:`jepsen_tpu.net.IpfilterNet` (net.clj:77-109).
"""

from __future__ import annotations

from jepsen_tpu import control as c
from jepsen_tpu import os_ as os_ns

BASE_PACKAGES = ["curl", "wget", "gnu-tar", "unzip", "psmisc"]


def installed(packages) -> set:
    """Which of the given packages are installed? (pkgin list)"""
    out = c.exec_("pkgin", "list", may_fail=True)
    have = set()
    for line in out.splitlines():
        name = line.split()[0] if line.split() else ""
        # pkgin prints name-version; strip the trailing version component.
        have.add(name.rsplit("-", 1)[0])
    return {p for p in packages if p in have}


def update() -> None:
    """Refresh the pkgin catalogue (smartos.clj pkgin update)."""
    with c.su():
        c.exec_("pkgin", "-y", "update")


def install(packages, force: bool = False) -> None:
    """Install missing packages idempotently via pkgin."""
    packages = list(packages)
    have = set() if force else installed(packages)
    missing = [p for p in packages if p not in have]
    if missing:
        with c.su():
            c.exec_("pkgin", "-y", "install", *missing)


def uninstall(packages) -> None:
    packages = list(packages)
    if packages:
        with c.su():
            c.exec_("pkgin", "-y", "remove", *packages)


def setup_hostfile(test, node) -> None:
    """Make the node refer to itself by its test name."""
    with c.su():
        c.exec_("hostname", node, may_fail=True)
        hosts = ["127.0.0.1 localhost", f"127.0.0.1 {node}"]
        c.exec_("tee", "/etc/hosts", stdin="\n".join(hosts) + "\n")


class SmartOS(os_ns.OS):
    """SmartOS setup: hostfile + base packages (smartos.clj os reify)."""

    def setup(self, test, node):
        setup_hostfile(test, node)
        install(BASE_PACKAGES)

    def teardown(self, test, node):
        pass


os = SmartOS()
