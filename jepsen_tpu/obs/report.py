"""Time attribution: where did the 3217 seconds go?

Consumes the tracer's events (live via ``trace.events()`` or a
finished run's JSONL spill via :func:`load`) and answers the question
every ROADMAP rung starts with: how much of a check's wall time is
device dispatch vs XLA compile vs host work, per call site and per
capacity level, and how much was wasted on failed escalation rungs.

Three outputs:

- :func:`attribution` / :func:`render` — the where-did-the-time-go
  table (``cli.py trace report``); per-site x per-cap wall seconds,
  the tunnel-overhead estimate (the ~100 ms/dispatch lore constant,
  CLAUDE.md), compile time, and wasted-rung cost. The number the mesh
  PR will be judged against.
- :func:`to_chrome` — Chrome/Perfetto trace-event JSON of the run
  timeline (``cli.py trace export --chrome``): complete ("X") events
  in microseconds, one row per thread, loadable in ui.perfetto.dev.
- :func:`summary` — the compact dict bench probes attach to their
  JSON artifacts.
"""

from __future__ import annotations

import json

# The shared-chip tunnel costs ~100 ms per dispatch (CLAUDE.md lore);
# the tunnel-overhead estimate is dispatches x this constant.
TUNNEL_S_PER_DISPATCH = 0.1


def load(path: str) -> list[dict]:
    """Events from a JSONL spill file (malformed lines skipped — a
    killed run's last line can be torn)."""
    out: list[dict] = []
    try:
        with open(path) as fh:
            for ln in fh:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    ev = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(ev, dict):
                    out.append(ev)
    except OSError:
        pass
    return out


def _shape_cap(shape: str | None):
    """The capacity coordinate of a supervise shape key
    (``site|rowsR|capC|wW|kernel``), or None."""
    if not shape:
        return None
    for part in str(shape).split("|"):
        if part.startswith("cap"):
            try:
                return int(part[3:])
            except ValueError:
                return None
    return None


def attribution(events: list[dict]) -> dict:
    """Aggregate events into the attribution dict ``render`` prints.

    ``total_s`` comes from the top-level "check" span(s); per-site
    rows from "dispatch" spans (every supervised engine dispatch);
    compile from "xla-compile"; wasted from the engines' wasted-rung /
    wave-trip instants plus non-ok dispatch wall. ``host_other_s`` is
    the remainder (packing, pruning bookkeeping, python) so the rows
    sum to the check wall."""
    sites: dict[str, dict] = {}
    other: dict[str, dict] = {}
    total_s = 0.0
    check_n = 0
    dispatch_s = 0.0
    dispatch_n = 0
    compile_s = 0.0
    compile_n = 0
    wasted_s = 0.0
    wasted_n = 0
    static_n = 0
    static_saved_s = 0.0
    ep_disp: list[int] = []
    ep_rows = 0
    for ev in events:
        name = ev.get("name")
        dur = float(ev.get("dur") or 0.0)
        args = ev.get("args") or {}
        if name == "check" and ev.get("ph") == "X":
            total_s += dur
            check_n += 1
        elif name == "xla-compile":
            compile_s += dur
            compile_n += 1
        elif name == "host-episode" and ev.get("ph") == "X":
            # Per-episode dispatch counts (bfs stamps the host-stats
            # deltas on the episode span): the histogram that reads
            # the episode scheduler's dispatch drop straight off a
            # probe-config5 trace — dispatches/episode before vs
            # after scheduling.
            if args.get("dispatches") is not None:
                ep_disp.append(int(args["dispatches"]))
                ep_rows += int(args.get("rows") or 0)
            o = other.setdefault("host-episode", {"n": 0,
                                                  "wall_s": 0.0})
            o["n"] += 1
            o["wall_s"] += dur
        elif name == "dispatch" and ev.get("ph") == "X":
            dispatch_s += dur
            dispatch_n += 1
            site = str(args.get("site") or "?")
            s = sites.setdefault(site, {"n": 0, "wall_s": 0.0,
                                        "ok": 0, "wedge": 0,
                                        "fault": 0, "caps": {}})
            s["n"] += 1
            s["wall_s"] += dur
            outcome = str(args.get("outcome") or "?")
            if outcome == "ok":
                s["ok"] += 1
            elif outcome.startswith("wedge"):
                s["wedge"] += 1
                wasted_s += dur
                wasted_n += 1
            else:
                s["fault"] += 1
                wasted_s += dur
                wasted_n += 1
            cap = _shape_cap(args.get("shape"))
            if cap is not None:
                s["caps"][cap] = s["caps"].get(cap, 0.0) + dur
        elif ev.get("ph") == "i" and name in ("wasted-rung",
                                              "wave-trip"):
            wasted_s += float(args.get("seconds") or 0.0)
            wasted_n += 1
        elif ev.get("ph") == "i" and name == "static-skip":
            # A dispatch the static gate routed away before it touched
            # the chip (analysis/gate): counted next to the wasted
            # rungs it is the predictive inverse of.
            static_n += 1
            static_saved_s += float(args.get("est_saved_s") or 0.0)
        elif ev.get("ph") == "X" and name:
            o = other.setdefault(str(name), {"n": 0, "wall_s": 0.0})
            o["n"] += 1
            o["wall_s"] += dur
    tunnel_est = dispatch_n * TUNNEL_S_PER_DISPATCH
    out = {
        "events": len(events),
        "total_s": round(total_s, 3), "checks": check_n,
        "dispatch_s": round(dispatch_s, 3), "dispatches": dispatch_n,
        "compile_s": round(compile_s, 3), "compiles": compile_n,
        "wasted_s": round(wasted_s, 3), "wasted_events": wasted_n,
        "static_skips": static_n,
        "static_saved_est_s": round(static_saved_s, 3),
        "tunnel_overhead_est_s": round(tunnel_est, 3),
        "device_busy_est_s": round(max(0.0, dispatch_s - tunnel_est),
                                   3),
        "sites": {k: {**v, "wall_s": round(v["wall_s"], 3),
                      "caps": {c: round(t, 3)
                               for c, t in sorted(v["caps"].items())}}
                  for k, v in sorted(sites.items())},
        "other": {k: {"n": v["n"], "wall_s": round(v["wall_s"], 3)}
                  for k, v in sorted(other.items())},
    }
    if ep_disp:
        hist: dict[str, int] = {}
        for d in ep_disp:
            # Power-of-two buckets: "1", "2-3", "4-7", "8-15", ...
            lo = 1
            while d > lo * 2 - 1:
                lo *= 2
            key = "1" if lo == 1 else f"{lo}-{lo * 2 - 1}"
            hist[key] = hist.get(key, 0) + 1
        out["episodes"] = {
            "n": len(ep_disp),
            "dispatches": sum(ep_disp),
            "rows": ep_rows,
            "dispatches_per_episode": round(
                sum(ep_disp) / len(ep_disp), 2),
            "rows_per_dispatch": round(
                ep_rows / max(1, sum(ep_disp)), 2),
            "histogram": hist,
        }
    if total_s > 0:
        out["host_other_s"] = round(max(0.0, total_s - dispatch_s), 3)
    return out


def render(agg: dict) -> str:
    """The attribution table as text (``cli.py trace report``)."""
    lines = []
    total = agg.get("total_s") or 0.0
    lines.append(f"trace: {agg.get('events', 0)} events, "
                 f"{agg.get('checks', 0)} check span(s)")
    lines.append(f"check wall total        {total:10.2f} s")

    def pct(x):
        return f"{100.0 * x / total:5.1f}%" if total > 0 else "    -"

    lines.append("")
    lines.append(f"{'site':<16}{'cap':>10}{'n':>7}{'wall s':>10}"
                 f"{'share':>8}{'ok':>5}{'wdg':>5}{'flt':>5}")
    for site, s in (agg.get("sites") or {}).items():
        caps = s.get("caps") or {}
        if caps:
            first = True
            for cap, t in caps.items():
                lines.append(
                    f"{site if first else '':<16}{cap:>10}"
                    f"{(s['n'] if first else ''):>7}{t:>10.2f}"
                    f"{pct(t):>8}"
                    f"{(s['ok'] if first else ''):>5}"
                    f"{(s['wedge'] if first else ''):>5}"
                    f"{(s['fault'] if first else ''):>5}")
                first = False
        else:
            lines.append(f"{site:<16}{'-':>10}{s['n']:>7}"
                         f"{s['wall_s']:>10.2f}{pct(s['wall_s']):>8}"
                         f"{s['ok']:>5}{s['wedge']:>5}{s['fault']:>5}")
    lines.append("")
    lines.append(f"dispatch wall           "
                 f"{agg.get('dispatch_s', 0.0):10.2f} s "
                 f"({agg.get('dispatches', 0)} dispatches)")
    lines.append(f"  tunnel overhead est   "
                 f"{agg.get('tunnel_overhead_est_s', 0.0):10.2f} s "
                 f"(~{TUNNEL_S_PER_DISPATCH * 1000:.0f} ms/dispatch)")
    lines.append(f"  device busy est       "
                 f"{agg.get('device_busy_est_s', 0.0):10.2f} s")
    lines.append(f"xla compile             "
                 f"{agg.get('compile_s', 0.0):10.2f} s "
                 f"({agg.get('compiles', 0)} compiles)")
    if "host_other_s" in agg:
        lines.append(f"host / other            "
                     f"{agg['host_other_s']:10.2f} s "
                     f"(packing, pruning bookkeeping, python)")
    lines.append(f"wasted (failed rungs)   "
                 f"{agg.get('wasted_s', 0.0):10.2f} s "
                 f"({agg.get('wasted_events', 0)} events)")
    ep = agg.get("episodes")
    if ep:
        # The episode scheduler's acceptance metric read off the
        # trace: dispatches per host episode, before vs after
        # scheduling (the wave path runs ~rows/4; the device-resident
        # scheduler ~1 per clean episode).
        hist = " ".join(f"{k}:{v}"
                        for k, v in sorted(
                            ep.get("histogram", {}).items(),
                            key=lambda kv: int(kv[0].split("-")[0])))
        lines.append(f"host episodes           {ep['n']:7d}    "
                     f"{ep['dispatches']} dispatches / {ep['rows']} "
                     f"rows ({ep['dispatches_per_episode']}/episode, "
                     f"{ep['rows_per_dispatch']} rows/dispatch)")
        lines.append(f"  dispatches/episode    {hist}")
    if agg.get("static_skips"):
        lines.append(f"avoided (static gate)   "
                     f"{agg.get('static_saved_est_s', 0.0):10.2f} s "
                     f"est ({agg['static_skips']} dispatch(es) routed "
                     f"pre-chip)")
    if agg.get("other"):
        lines.append("")
        lines.append("other spans: " + ", ".join(
            f"{k} n={v['n']} {v['wall_s']:.2f}s"
            for k, v in agg["other"].items()))
    return "\n".join(lines)


def summary(events: list[dict]) -> dict:
    """Compact attribution for bench probe artifacts: the headline
    numbers without the per-site table bulk."""
    agg = attribution(events)
    keys = ("events", "total_s", "dispatch_s", "dispatches",
            "compile_s", "compiles", "wasted_s", "static_skips",
            "static_saved_est_s", "tunnel_overhead_est_s",
            "device_busy_est_s", "host_other_s")
    out = {k: agg[k] for k in keys if k in agg}
    out["site_s"] = {k: v["wall_s"]
                     for k, v in (agg.get("sites") or {}).items()}
    return out


def to_chrome(events: list[dict]) -> dict:
    """Chrome/Perfetto trace-event JSON (the "JSON Array Format" with
    a ``traceEvents`` wrapper): monotonic-seconds events become
    microsecond "X" (complete) / "i" (instant) events, timestamps
    rebased to the earliest event so Perfetto opens at t=0."""
    if events:
        t_base = min(float(e.get("ts") or 0.0) for e in events)
    else:
        t_base = 0.0
    out = []
    for ev in events:
        args = dict(ev.get("args") or {})
        name = str(ev.get("name") or "?")
        site = args.get("site")
        rec = {"name": f"{name}:{site}" if site else name,
               "cat": name,
               "ph": "i" if ev.get("ph") == "i" else "X",
               "ts": round((float(ev.get("ts") or 0.0) - t_base) * 1e6,
                           1),
               "pid": int(ev.get("pid") or 0),
               "tid": int(ev.get("tid") or 0) % 2**31,
               "args": args}
        if rec["ph"] == "X":
            rec["dur"] = round(float(ev.get("dur") or 0.0) * 1e6, 1)
        else:
            rec["s"] = "t"
        out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms"}
