"""Span tracer: the flight recorder's event source.

A thread-safe, env-gated (``JEPSEN_TPU_TRACE=1``) tracer with a
context-manager API over monotonic clocks::

    from jepsen_tpu.obs import trace
    with trace.span("dispatch", site="host-fixpoint", cap=4096) as sp:
        ...
        sp.note(outcome="ok", passes=7)

Disabled (the default), :func:`span` returns one shared
:data:`NULL_SPAN` object — no span object, no event, no buffer touch
per call — so the quick tier and untraced production runs pay only an
``os.environ`` lookup. Enabled, completed spans land in a bounded
in-memory buffer (``JEPSEN_TPU_TRACE_BUF`` events) that SPILLS to a
JSONL file (``JEPSEN_TPU_TRACE_FILE``, default
``<repo>/.jax_cache/trace.jsonl``; ``0`` disables the file) instead of
dropping — a killed run keeps everything already spilled, and
``atexit`` flushes the tail. One process per file: the first write of
a process truncates it, so ``cli.py trace report`` reads the most
recent run.

Event shape (one JSON object per line)::

    {"name": ..., "ph": "X"|"i", "ts": <monotonic s>, "dur": <s>,
     "pid": ..., "tid": ..., "depth": <span nesting>, "args": {...}}

Timestamps are ``time.monotonic()`` seconds (relative, clock-nemesis
immune); :func:`jepsen_tpu.obs.report.to_chrome` converts to the
microsecond trace-event format Perfetto loads.

The tracer observes — it never routes, retries, or alters engine
behaviour.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time

from jepsen_tpu import util


def enabled() -> bool:
    """Master switch: ``JEPSEN_TPU_TRACE=1``. Re-read per call (the
    env-knob convention, doc/env.md) — one dict lookup on the disabled
    path."""
    return os.environ.get("JEPSEN_TPU_TRACE", "") not in ("", "0")


def trace_file() -> str | None:
    """The JSONL spill path; ``JEPSEN_TPU_TRACE_FILE=0`` keeps the
    trace purely in-memory (tests)."""
    env = os.environ.get("JEPSEN_TPU_TRACE_FILE", "")
    if env == "0":
        return None
    if env:
        return env
    return os.path.join(util.cache_dir(), "trace.jsonl")


def buf_cap() -> int:
    return util.env_int("JEPSEN_TPU_TRACE_BUF", 65536)


def max_bytes() -> int:
    """Spill-file rotation threshold (``JEPSEN_TPU_TRACE_MAX_MB``,
    default 256 MB; ``0`` = unlimited): looped probes under
    ``JEPSEN_TPU_TRACE=1`` must not grow the spill unbounded. Past it
    the live file rotates to ``<path>.1`` (one generation kept) and
    the spill continues fresh — ``trace report`` keeps reading the
    live path, which holds the newest events, and :func:`rotations`
    lets producers note the rotation in their perf-ledger record."""
    try:
        mb = util.env_float("JEPSEN_TPU_TRACE_MAX_MB", 256.0)
    except ValueError:
        # A malformed knob must not escape _flush_locked mid-dispatch
        # (run_guarded would read it as a device fault): tracing must
        # never take a run down — fall back to the default cap.
        mb = 256.0
    return int(mb * 1024 * 1024) if mb > 0 else 0


# Spill well before the ring cap so a configured file loses nothing;
# without a file the buffer is a true ring (oldest events drop).
_SPILL_BATCH = 4096
# Batch spills keep the newest events in memory so a tail_note()
# landing just after the boundary still reaches the file copy (the
# final flush writes everything).
_SPILL_KEEP = 64

_lock = threading.Lock()
_buf: list[dict] = []
_spilled = 0
_rotations = 0
_file_started = False
_file_dead = False
_atexit_on = False
_tls = threading.local()


class _NullSpan:
    """The disabled-path singleton: enter/exit/note are no-ops."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def note(self, **kw):
        pass


NULL_SPAN = _NullSpan()


class Span:
    """One traced interval. Use via :func:`span` as a context manager;
    ``note(**kw)`` merges attributes into the event's ``args`` (e.g.
    the outcome, pass counts). An exception exiting the span stamps
    ``outcome="error:<Type>"`` unless the site noted one already."""

    __slots__ = ("name", "meta", "_t0")

    def __init__(self, name: str, meta: dict):
        self.name = name
        self.meta = meta
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.monotonic()
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self)
        return self

    def note(self, **kw):
        self.meta.update(kw)

    def __exit__(self, et, ev, tb):
        end = time.monotonic()
        stack = getattr(_tls, "stack", None)
        depth = 0
        if stack:
            try:
                stack.remove(self)
            except ValueError:
                pass
            depth = len(stack)
        if et is not None and "outcome" not in self.meta:
            self.meta["outcome"] = f"error:{et.__name__}"
        _record({"name": self.name, "ph": "X", "ts": self._t0,
                 "dur": end - self._t0, "pid": os.getpid(),
                 "tid": threading.get_ident(), "depth": depth,
                 "args": self.meta})
        return False


def span(name: str, **meta):
    """A new :class:`Span` (or :data:`NULL_SPAN` when tracing is off)."""
    if not enabled():
        return NULL_SPAN
    return Span(name, meta)


def tail_note(**kw) -> None:
    """Annotate the most recently COMPLETED event on this thread —
    how call sites attach after-the-fact data (frontier count, pass
    totals) to a span that ended inside a helper (supervise.call)."""
    if not enabled():
        return
    ev = getattr(_tls, "last", None)
    if ev is not None:
        ev["args"].update(kw)


def complete(name: str, t0: float, dur_s: float, **meta) -> None:
    """Retro-record a completed interval (``t0`` in ``time.monotonic``
    seconds) — for lifecycles that cross threads (the checker daemon's
    admit->finish request path) or are measured externally (XLA
    compiles)."""
    if not enabled():
        return
    _record({"name": name, "ph": "X", "ts": t0, "dur": dur_s,
             "pid": os.getpid(), "tid": threading.get_ident(),
             "depth": 0, "args": meta})


def instant(name: str, **meta) -> None:
    """A point event (wasted escalation rung, wave trip, quarantine
    hit)."""
    if not enabled():
        return
    _record({"name": name, "ph": "i", "ts": time.monotonic(),
             "dur": 0.0, "pid": os.getpid(),
             "tid": threading.get_ident(), "depth": 0, "args": meta})


def _record(ev: dict) -> None:
    global _atexit_on
    _tls.last = ev
    with _lock:
        _buf.append(ev)
        if not _atexit_on:
            _atexit_on = True
            atexit.register(flush)
        path = None if _file_dead else trace_file()
        if path is not None:
            if len(_buf) >= _SPILL_BATCH:
                _flush_locked(path, keep=_SPILL_KEEP)
        else:
            cap = buf_cap()
            if len(_buf) > cap:
                del _buf[:len(_buf) - cap]


def _flush_locked(path: str, keep: int = 0) -> None:
    global _file_started, _spilled
    n = len(_buf) - keep
    if n <= 0:
        return
    # Serialize BEFORE touching the file, per event and exception-safe:
    # a tail_note() from another thread can mutate an args dict mid-
    # dumps (RuntimeError), and any failure escaping here would surface
    # inside an engine dispatch where run_guarded reads it as a device
    # fault and quarantines a healthy shape. Tracing must never take a
    # run down — a still-unserializable event is dropped, not fatal.
    lines = []
    for ev in _buf[:n]:
        try:
            lines.append(json.dumps(ev, default=str))
        except Exception:  # noqa: BLE001 - concurrent args mutation
            try:
                ev = dict(ev, args=dict(ev.get("args") or {}))
                lines.append(json.dumps(ev, default=str))
            except Exception:  # noqa: BLE001
                pass
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # Spill hygiene (JEPSEN_TPU_TRACE_MAX_MB): a live file already
        # past the cap rotates to <path>.1 BEFORE this write (one
        # generation kept), so looped probes can't fill the disk and
        # the configured path always exists holding the NEWEST events
        # — `trace report` reads it unchanged. Best-effort: rotation
        # failure degrades to an uncapped file, never a lost run.
        cap_bytes = max_bytes()
        if cap_bytes and _file_started:
            try:
                if os.path.getsize(path) >= cap_bytes:
                    os.replace(path, path + ".1")
                    _file_started = False
                    global _rotations
                    _rotations += 1
            except OSError:
                pass
        mode = "a" if _file_started else "w"
        with open(path, mode) as fh:
            for ln in lines:
                fh.write(ln + "\n")
        _file_started = True
        _spilled += n
        del _buf[:n]
    except OSError:
        # Spill failure degrades PERMANENTLY to the in-memory ring
        # (reset() re-arms): without the latch every later _record
        # would re-serialize the whole >=_SPILL_BATCH backlog under
        # the lock — an O(n^2) tax inside the engine dispatch path.
        # Tracing must never take a run down.
        global _file_dead
        _file_dead = True
        cap = buf_cap()
        if len(_buf) > cap:
            del _buf[:len(_buf) - cap]


def flush(path: str | None = None) -> str | None:
    """Write buffered events to the JSONL file (atexit calls this);
    returns the path, or None when the file is disabled."""
    with _lock:
        p = path or trace_file()
        if p is not None:
            _flush_locked(p)
        return p


def events() -> list[dict]:
    """Snapshot of the in-memory buffer (NOT the spilled file — use
    :func:`jepsen_tpu.obs.report.load` for a finished run's file)."""
    with _lock:
        return list(_buf)


def spilled() -> int:
    """Events already written to the spill file this process."""
    return _spilled


def rotations() -> int:
    """Spill-file rotations this process (``JEPSEN_TPU_TRACE_MAX_MB``)
    — producers stamp it into their perf-ledger record so a truncated
    trace summary is attributable."""
    return _rotations


def reset() -> None:
    """Drop all in-memory state (tests; the next flush truncates the
    file again so a test's trace file holds only its own run)."""
    global _spilled, _rotations, _file_started, _file_dead
    with _lock:
        _buf.clear()
        _spilled = 0
        _rotations = 0
        _file_started = False
        _file_dead = False
    _tls.last = None
    _tls.stack = []


# XLA compiles as trace events: the compile meter (util) runs the hook
# after every true backend compile; enabled() gating lives in
# complete().
def _on_compile(t0: float, dur_s: float) -> None:
    complete("xla-compile", t0, dur_s)


util.add_compile_hook(_on_compile)
