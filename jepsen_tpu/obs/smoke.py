"""`make trace-smoke`: traced check -> report -> export -> /run page.

A FRESH-process, chip-free proof (the serve-smoke contract: forces the
CPU platform itself, before any backend init) that the flight recorder
works end to end: a small sparse-engine history decides with
``JEPSEN_TPU_TRACE=1``, and then

- the attribution report renders with the check's dispatch sites,
- the Chrome export is structurally valid trace-event JSON,
- the registry snapshot exists and ``web.py /run`` renders it,
- the traced verdict matches the CPU oracle (the tracer observes, it
  never routes).

Prints one JSON result line and exits 0/1 — timeout-guarded by the
Makefile so a wedge cannot hold the shell. Artifacts land in
``.jax_cache/`` (trace_smoke.trace.jsonl / trace_smoke.telemetry.json)
so ``cli.py trace report`` works on the smoke's own output.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> int:
    t_start = time.time()
    # CPU mesh BEFORE any jax backend init (CLAUDE.md: the TPU plugin
    # force-selects its platform; the smoke must never take the chip).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    os.environ["JEPSEN_TPU_TRACE"] = "1"
    os.environ.setdefault(
        "JEPSEN_TPU_TRACE_FILE",
        os.path.join(".jax_cache", "trace_smoke.trace.jsonl"))
    os.environ.setdefault(
        "JEPSEN_TPU_OBS_SNAPSHOT",
        os.path.join(".jax_cache", "trace_smoke.telemetry.json"))

    from jepsen_tpu import models as m
    from jepsen_tpu import web
    from jepsen_tpu.lin import cpu, device_check_packed, prepare, synth
    from jepsen_tpu.obs import metrics, report, trace
    from jepsen_tpu.util import enable_compile_cache

    enable_compile_cache()
    # A wide-window register history (window ~26, past the dense
    # engine's W<=20 bound): routes to the sparse chunked engine, so
    # the trace carries real supervised dispatch spans (site
    # "chunk"/"chunk-batch"), not just the top-level check span.
    h = synth.generate_register_history(
        500, concurrency=30, seed=7, value_range=5,
        crash_prob=0.002, max_crashes=4)
    p = prepare.prepare(m.cas_register(), h)
    want = cpu.check_packed(p)["valid?"]
    r = device_check_packed(p)

    out = {"events": len(trace.events()), "verdict": r.get("valid?"),
           "want": want}
    ok = r.get("valid?") == want and out["events"] > 0

    # Report renders and attributes the dispatch sites.
    agg = report.attribution(trace.events())
    text = report.render(agg)
    out["report"] = {"total_s": agg["total_s"],
                     "dispatches": agg["dispatches"],
                     "sites": sorted(agg["sites"])}
    ok = ok and agg["checks"] >= 1 and agg["dispatches"] >= 1 \
        and "check wall total" in text

    # Chrome export: structurally valid trace-event JSON.
    chrome = report.to_chrome(trace.events())
    out["chrome_events"] = len(chrome["traceEvents"])
    ok = ok and chrome["traceEvents"] and all(
        ev["ph"] in ("X", "i") and isinstance(ev["ts"], (int, float))
        for ev in chrome["traceEvents"])

    # Spill + snapshot on disk; /run renders the snapshot.
    spill = trace.flush()
    out["trace_file"] = spill
    ok = ok and spill is not None and len(report.load(spill)) \
        >= out["events"]
    metrics.REGISTRY.write_snapshot(force=True)
    snap_path = metrics.snapshot_path()
    html = web.run_html(snap_path)
    out["snapshot"] = snap_path
    ok = ok and "run telemetry" in html and "host-stats" in html

    out["ok"] = bool(ok)
    # Cross-run perf ledger (doc/observability.md § Perf ledger): the
    # smoke records its own run like every evidence producer; record()
    # never raises, so a ledger failure cannot cost the smoke verdict.
    from jepsen_tpu.obs import ledger as perf_ledger

    perf_ledger.record(
        "trace-smoke", kind="smoke", wall_s=time.time() - t_start,
        verdict=bool(ok),
        trace={k: agg.get(k) for k in ("total_s", "dispatches",
                                       "compile_s")})
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
