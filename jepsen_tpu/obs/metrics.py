"""Typed metrics registry: one snapshot for every stats dict.

Before this module, the checker stack grew three divergent dict
conventions for the same job — the host-row executor's ``host-stats``,
sharded's ``mesh-stats``, and the service daemon's stats — each with
its own snapshot writer and reader. Here they become named VIEWS of
one registry: the engines register their live stats dicts (still
plain dicts, still bumped via :func:`jepsen_tpu.util.stat_bump` /
``stat_time`` so verdict shapes are unchanged), and the registry
serializes them all through one codec (``util.round_stats`` +
``util.write_json_atomic``) into one snapshot file.

On top of the views the registry carries RUN telemetry:

- gauges (current row, total rows, frontier size),
- a bounded sample ring of ``(elapsed_s, row, frontier)`` — the
  rows/s, ETA, and frontier sparkline behind ``web.py /run``,
- a bounded event feed (watchdog wedges, faults, quarantine records —
  pushed by ``lin/supervise``) so a wedged config-5 run is diagnosable
  from the snapshot file without attaching a debugger,
- the process-wide XLA compile meter (``util.compile_meter``).

``progress()`` is the engines' one call per committed row boundary; it
is cheap (dict stores + a deque append) and interval-gates the
snapshot write (``JEPSEN_TPU_OBS_EVERY_S``, default 5 s) so short runs
and tests write nothing. ``JEPSEN_TPU_OBS_SNAPSHOT=0`` disables the
file entirely.

jax-free at import time: web.py and the CLI load this module without
dragging a backend in.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from jepsen_tpu import util

MAX_SAMPLES = 256
MAX_EVENTS = 64


def snapshot_path() -> str | None:
    """The run-telemetry snapshot file (``web.py /run``, ``cli.py
    host-stats``); ``JEPSEN_TPU_OBS_SNAPSHOT=0`` disables it."""
    env = os.environ.get("JEPSEN_TPU_OBS_SNAPSHOT", "")
    if env == "0":
        return None
    if env:
        return env
    return os.path.join(util.cache_dir(), "run_telemetry.json")


def snapshot_every_s() -> float:
    return util.env_float("JEPSEN_TPU_OBS_EVERY_S", 5.0)


def load_json_snapshot(path) -> tuple[dict | None, str | None]:
    """THE shared snapshot-file loader: ``(snap, None)`` on success,
    ``(None, reason)`` on a missing/corrupt file. web.py's /service,
    /txn, and /run pages and the CLI's service-stats / host-stats
    commands all read snapshots through this one helper instead of
    hand-rolling open/load/fallback at each site."""
    try:
        with open(path) as fh:
            return json.load(fh), None
    except (OSError, ValueError, TypeError) as e:
        return None, str(e)


class Registry:
    """The process-wide metrics registry (module-level ``REGISTRY``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._views: dict[str, dict] = {}
        self._gauges: dict = {}
        self._counters: dict = {}
        self._samples: deque = deque(maxlen=MAX_SAMPLES)
        self._events: deque = deque(maxlen=MAX_EVENTS)
        self._run_t0: float | None = None
        # Gate the FIRST interval too: a run must live past
        # JEPSEN_TPU_OBS_EVERY_S before anything hits disk — the
        # "short runs and tests write nothing" promise.
        self._last_write = time.monotonic()

    # --- views --------------------------------------------------------------

    def view(self, name: str, stats: dict | None = None) -> dict:
        """Register (or fetch) a named view. ``stats`` is held by
        LIVE reference — the engine keeps bumping its own dict and the
        snapshot sees the current values; re-registering a name swaps
        the reference (each check run registers its fresh stats)."""
        with self._lock:
            if stats is not None:
                self._views[name] = stats
            return self._views.setdefault(name, {})

    # --- typed accessors ----------------------------------------------------

    def counter(self, key: str, n: int = 1) -> None:
        with self._lock:
            util.stat_bump(self._counters, key, n)

    def gauge(self, key: str, value) -> None:
        with self._lock:
            self._gauges[key] = value

    def timer(self, view: str, key: str, bucket, seconds: float) -> None:
        """``stat_time`` into a named view (creates the view)."""
        with self._lock:
            util.stat_time(self._views.setdefault(view, {}), key,
                           bucket, seconds)

    def event(self, kind: str, **fields) -> None:
        """Append to the bounded event feed (watchdog trips, faults,
        quarantine records — the /run page's triage column). Also
        bumps a durable ``event_<kind>`` counter: the ring evicts past
        MAX_EVENTS, so long chaos runs audit counts, not the feed."""
        with self._lock:
            e = {"t": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                    time.gmtime()),
                 "kind": kind}
            e.update(fields)
            self._events.append(e)
            util.stat_bump(self._counters, f"event_{kind}")

    # --- run progress -------------------------------------------------------

    def start_run(self, name: str, total: int | None = None,
                  **gauges) -> None:
        """Reset run telemetry at the top of a check (the engines call
        this once per ``check_packed``); views persist across runs."""
        with self._lock:
            self._run_t0 = time.monotonic()
            self._last_write = self._run_t0
            self._samples.clear()
            self._gauges = {"run": name,
                            "started": time.strftime(
                                "%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
            if total is not None:
                self._gauges["total_rows"] = int(total)
            self._gauges.update(gauges)

    def progress(self, row: int | None = None,
                 frontier: int | None = None, **gauges) -> None:
        """One committed-row-boundary tick: update gauges, append a
        sparkline sample, and (interval-gated) write the snapshot."""
        with self._lock:
            if self._run_t0 is None:
                self._run_t0 = time.monotonic()
            if row is not None:
                self._gauges["row"] = int(row)
            if frontier is not None:
                self._gauges["frontier"] = int(frontier)
            self._gauges.update(gauges)
            self._samples.append(
                (round(time.monotonic() - self._run_t0, 2),
                 None if row is None else int(row),
                 None if frontier is None else int(frontier)))
        self.write_snapshot()

    # --- snapshot -----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            gauges = dict(self._gauges)
            samples = [list(s) for s in self._samples]
            events = [dict(e) for e in self._events]
            counters = dict(self._counters)
            views = {name: util.round_stats(dict(d), 3)
                     for name, d in self._views.items() if d}
        run = dict(gauges)
        rowed = [s for s in samples if s[1] is not None]
        if len(rowed) >= 2:
            dt = rowed[-1][0] - rowed[0][0]
            drow = rowed[-1][1] - rowed[0][1]
            if dt > 0 and drow > 0:
                rps = drow / dt
                run["rows_per_sec"] = round(rps, 2)
                total = gauges.get("total_rows")
                if total:
                    run["eta_s"] = round(
                        max(0, total - rowed[-1][1]) / rps, 1)
        out = {"updated": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime()),
               "pid": os.getpid(), "run": run, "samples": samples,
               "events": events, "views": views}
        if counters:
            out["counters"] = counters
        out.update(util.compile_meter())
        return out

    def write_snapshot(self, path: str | None = None,
                       force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_write < snapshot_every_s():
            return
        p = path or snapshot_path()
        if p is None:
            return
        self._last_write = now
        try:
            util.write_json_atomic(p, self.snapshot(), default=str)
        except Exception:  # noqa: BLE001 - observability must never
            pass           # take an engine run down

    def reset(self) -> None:
        """Tests only: drop every view, gauge, sample, and event."""
        with self._lock:
            self._views.clear()
            self._gauges = {}
            self._counters = {}
            self._samples.clear()
            self._events.clear()
            self._run_t0 = None
            self._last_write = time.monotonic()


REGISTRY = Registry()
