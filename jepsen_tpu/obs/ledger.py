"""Cross-run perf ledger: every bench/probe/smoke run recorded.

Jepsen keeps durable evidence of every run (``jepsen.store`` run
directories, ``checker.perf`` graphs); the TPU rebuild had the in-run
flight recorder (obs/trace) but no CROSS-run memory — the bench
trajectory lived in BENCH_*.json files nothing collated, and a perf
regression or verdict flip introduced by an engine change stayed
invisible until someone re-read raw JSON. This module is that memory:

- an APPEND-ONLY JSONL ledger (``JEPSEN_TPU_PERF_LEDGER``, default
  ``<repo>/.jax_cache/perf_ledger.jsonl``; ``0`` disables) that every
  evidence producer writes ONE record into — each ``bench.py`` probe
  rung (via ``_probe_main``), the headline, ``make probe-config5``,
  and the five chip-free smokes (serve/txn/trace/stream/fleet) plus
  ``make perf-smoke``;
- each record stamped with the git sha, the platform (``cpu`` mesh vs
  ``tpu``), an env-knob FINGERPRINT of the forced rung config (every
  ``JEPSEN_TPU_*`` var in the environment), and the quarantine-ledger
  delta the run produced;
- an atomic ``<ledger>.index.json`` summary (``util.write_json_atomic``)
  for monitoring without parsing the JSONL;
- :func:`trend` — the per-(probe, platform) trend table behind
  ``cli.py perf report`` and ``web.py /perf``;
- :func:`diff` — records appended since a prior snapshot (the
  ``quarantine diff`` precedent; ``make probe-config5`` prints it);
- :func:`gate` — the CI-consumable regression sentinel behind
  ``cli.py perf gate``: nonzero exit on a verdict flip vs the last
  same-shape record (hard fail), wall_s past
  ``JEPSEN_TPU_PERF_GATE_FRAC`` (1.5x) of the trailing median, new
  quarantine entries, or dispatches/episode growth.

Writes are FAULT-ISOLATED at the producer: :func:`record` never
raises, so a ledger I/O failure can never cost a probe result (the
loss-proof bench contract). The reader is torn-tail tolerant — a
SIGKILL mid-append costs one record, never the ledger.

jax-free at import time (web.py and the CLI load this without dragging
a backend in); platform detection only consults jax when the caller's
process already imported it.
"""

from __future__ import annotations

import hashlib
import json
import os
import statistics
import sys
import time

from jepsen_tpu import util

# Trailing-window length for medians (wall seconds, dispatches/episode):
# long enough to ride out tunnel variance (bench takes best-of-3 for
# the same reason), short enough that a genuine perf change re-anchors
# the baseline within a few runs.
TRAIL = 8
# Minimum prior same-shape records before the RATIO gates (wall,
# dispatches/episode) fire: one sample is not a trend on a shared-chip
# tunnel with run-to-run variance. The verdict-flip and new-quarantine
# gates need only one prior record / none.
MIN_TREND = 2

_TS_FMT = "%Y-%m-%dT%H:%M:%SZ"


def ledger_path() -> str | None:
    """The perf ledger path; ``JEPSEN_TPU_PERF_LEDGER=0`` disables
    recording entirely (tests that must not touch the shared file set
    their own path instead)."""
    env = os.environ.get("JEPSEN_TPU_PERF_LEDGER", "")
    if env == "0":
        return None
    if env:
        return env
    return os.path.join(util.cache_dir(), "perf_ledger.jsonl")


def gate_frac() -> float:
    """Regression threshold: a run slower than ``frac`` x the trailing
    median (or with dispatches/episode grown past it) fails the gate."""
    return util.env_float("JEPSEN_TPU_PERF_GATE_FRAC", 1.5)


# --- record construction ----------------------------------------------------


def _git_sha(root: str | None = None) -> str | None:
    """HEAD's sha read straight off ``.git`` (no subprocess — the
    ledger writes from inside probe children where a fork can race a
    teardown). Linked WORKTREES (``.git`` is a ``gitdir: ...`` file)
    resolve HEAD under their private gitdir and refs/packed-refs under
    the shared commondir. None when the checkout has no readable git
    state."""
    root = root or os.path.dirname(util.cache_dir())
    git = os.path.join(root, ".git")
    try:
        if os.path.isfile(git):
            with open(git) as fh:
                head_line = fh.read().strip()
            if not head_line.startswith("gitdir:"):
                return None
            git = head_line.split(":", 1)[1].strip()
            if not os.path.isabs(git):
                git = os.path.join(root, git)
        # Refs live under the COMMON dir when this is a worktree's
        # private gitdir; HEAD stays private.
        common = git
        try:
            with open(os.path.join(git, "commondir")) as fh:
                common = os.path.normpath(
                    os.path.join(git, fh.read().strip()))
        except OSError:
            pass
        with open(os.path.join(git, "HEAD")) as fh:
            head = fh.read().strip()
        if head.startswith("ref:"):
            ref = head.split(None, 1)[1]
            try:
                with open(os.path.join(common, ref)) as fh:
                    return fh.read().strip()[:12]
            except OSError:
                # Packed refs (post-gc): scan the one flat file.
                with open(os.path.join(common, "packed-refs")) as fh:
                    for ln in fh:
                        parts = ln.split()
                        # Exact ref-name match ("<sha> <refname>") —
                        # endswith would let refs/backup/refs/heads/X
                        # shadow refs/heads/X.
                        if len(parts) == 2 and parts[1] == ref:
                            return parts[0][:12]
                return None
        return head[:12]
    except OSError:
        return None


def _platform() -> dict:
    """Platform stamp: jax's platform + device count when jax is
    ALREADY loaded in this process (probes, smokes); ``host``
    otherwise. Never imports jax — the ledger is also written from
    jax-free tooling and must not drag a backend in."""
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            devs = jax.devices()
            return {"platform": devs[0].platform, "devices": len(devs)}
        except Exception:  # noqa: BLE001 - backend init can fail late
            pass
    return {"platform": "host", "devices": 0}


def env_fingerprint(overlay: dict | None = None) -> tuple[dict, str]:
    """The forced rung config as evidence: every ``JEPSEN_TPU_*`` var
    set in this environment (the bench ladder forces each rung's knobs
    explicitly, so the child environment IS the rung config), plus a
    short stable hash for same-config grouping. ``overlay`` merges a
    config the caller forced on a DIFFERENT process — how the bench
    parent records a killed child's rung config instead of its own."""
    env = {k: v for k, v in os.environ.items()
           if k.startswith("JEPSEN_TPU_")}
    if overlay:
        env.update({k: v for k, v in overlay.items()
                    if k.startswith("JEPSEN_TPU_")})
    env = dict(sorted(env.items()))
    fp = hashlib.sha256(
        json.dumps(env, sort_keys=True).encode()).hexdigest()[:10]
    return env, fp


def _sum_bucketed(v) -> float | None:
    """Total of a per-cap timing histogram (``stat_time`` dicts); a
    bare number passes through."""
    if isinstance(v, dict):
        try:
            return round(sum(float(x) for x in v.values()), 3)
        except (TypeError, ValueError):
            return None
    if isinstance(v, (int, float)):
        return float(v)
    return None


def make_record(probe: str, *, wall_s=None, verdict=None,
                kind: str = "probe", host_stats=None, trace=None,
                fleet=None, error=None, quarantine_new=None,
                extra=None, env_overlay=None) -> dict:
    """One ledger record. ``probe`` is the trend-row tag (a bench probe
    key, a partitioned rung tag like ``partitioned_c30.sched``, or a
    smoke name); ``host_stats``/``trace``/``fleet`` ride verbatim (the
    engines' counters), with the headline derivatives (dispatches,
    episodes, dispatches/episode, wasted seconds) lifted to the top
    level so trend/gate never dig."""
    env, fp = env_fingerprint(env_overlay)
    plat = _platform()
    rec = {"t": time.strftime(_TS_FMT, time.gmtime()),
           "probe": str(probe), "kind": kind,
           "platform": plat["platform"], "devices": plat["devices"],
           "git": _git_sha(), "env_fp": fp, "env": env,
           "wall_s": None if wall_s is None else round(float(wall_s), 3),
           "verdict": verdict}
    if error:
        rec["error"] = str(error)[:500]
    hs = host_stats or {}
    if hs:
        rec["host_stats"] = util.round_stats(dict(hs), 3)
        disp = hs.get("dispatches")
        eps = hs.get("episodes")
        if disp is not None:
            rec["dispatches"] = int(disp)
        if eps is not None:
            rec["episodes"] = int(eps)
            if disp and eps:
                rec["dispatches_per_episode"] = round(disp / eps, 2)
        wasted = _sum_bucketed(hs.get("wasted_seconds"))
        if wasted is not None:
            rec["wasted_seconds"] = round(wasted, 3)
    if trace:
        rec["trace"] = trace
    if fleet:
        rec["fleet"] = fleet
    if quarantine_new:
        rec["quarantine_new"] = sorted(quarantine_new)
    if extra:
        rec.update(extra)
    return rec


# --- append + index ---------------------------------------------------------


def append(rec: dict, path: str | None = None) -> str | None:
    """Append one record (a newline-terminated JSON line, flushed) and
    refresh the atomic index. Returns the path, or None when the
    ledger is disabled. RAISES on I/O failure — producers go through
    :func:`record`, which swallows (a ledger failure must never cost a
    probe result)."""
    path = path or ledger_path()
    if path is None:
        return None
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # Newline-heal a torn tail (util helper shared with the service
    # journal): a SIGKILL mid-append can leave a partial line;
    # appending straight after it would glue two records into one
    # unparseable line.
    heal = b"\n" if util.file_needs_newline_heal(path) else b""
    try:
        pre_size = os.path.getsize(path)
    except OSError:
        pre_size = 0
    buf = heal + json.dumps(rec, default=str).encode() + b"\n"
    with open(path, "ab") as fh:
        fh.write(buf)
        fh.flush()
    _write_index(path, rec, pre_size=pre_size,
                 post_size=pre_size + len(buf))
    return path


def record(probe: str, path: str | None = None, **kw) -> dict | None:
    """:func:`make_record` + :func:`append`, NEVER raises — the
    producer-facing entry point (bench probes, smokes). Returns the
    record, or None when disabled or the write failed."""
    try:
        rec = make_record(probe, **kw)
        if append(rec, path) is None:
            return None
        return rec
    except Exception:  # noqa: BLE001 - observability must never cost
        return None    # a probe result (the loss-proof contract)


def _bump_index(probes: dict, r: dict) -> None:
    p = probes.setdefault(str(r.get("probe")), {"n": 0})
    p["n"] += 1
    p["last_t"] = r.get("t")
    p["last_wall_s"] = r.get("wall_s")
    p["last_verdict"] = r.get("verdict")
    p["last_git"] = r.get("git")


def _write_index(path: str, rec: dict | None = None,
                 pre_size: int | None = None,
                 post_size: int | None = None) -> None:
    """``<ledger>.index.json``: per-probe last/total summary for
    monitoring without parsing the JSONL. INCREMENTAL when the prior
    index parses AND its stamped ledger byte-size matches the file
    size this append started from (O(1) staleness detection: another
    producer's append — or a crash between JSONL write and index
    write — changes the size, and the next append self-heals with a
    full rebuild); otherwise rebuilds from the JSONL. Best-effort
    (the ledger line already landed)."""
    try:
        idx = None
        if rec is not None and pre_size is not None:
            try:
                with open(path + ".index.json") as fh:
                    idx = json.load(fh)
            except (OSError, ValueError):
                idx = None
            if not (isinstance(idx, dict)
                    and isinstance(idx.get("records"), int)
                    and isinstance(idx.get("probes"), dict)
                    and idx.get("bytes") == pre_size):
                idx = None
        if idx is not None:
            idx["records"] += 1
            _bump_index(idx["probes"], rec)
            # Stamp OUR append's end offset, never the live getsize():
            # a concurrent producer's bytes landing between our write
            # and a getsize() would be folded into the stamp as if
            # counted, defeating the next append's staleness check.
            idx["bytes"] = post_size
        else:
            probes: dict[str, dict] = {}
            recs = load(path)
            for r in recs:
                _bump_index(probes, r)
            idx = {"records": len(recs), "probes": probes,
                   "bytes": os.path.getsize(path)}
        idx["updated"] = time.strftime(_TS_FMT, time.gmtime())
        util.write_json_atomic(path + ".index.json", idx, default=str)
    except Exception:  # noqa: BLE001 - index is derived state
        pass


def load(path: str | None = None) -> list[dict]:
    """Every parseable record, in append order. Torn/garbage lines are
    skipped (a killed run's tail is expected, not fatal); a missing
    file is an empty ledger."""
    path = path or ledger_path()
    out: list[dict] = []
    if path is None:
        return out
    try:
        with open(path) as fh:
            for ln in fh:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("probe"):
                    out.append(rec)
    except OSError:
        pass
    return out


# --- trend ------------------------------------------------------------------


def group_key(rec: dict) -> str:
    """Trend-row identity: (probe, platform). The env fingerprint is
    stamped per record for forensics but does NOT split rows — a knob
    change that moves a probe's cost should be visible IN its trend,
    not hidden in a fresh row."""
    return f"{rec.get('probe')}|{rec.get('platform')}"


def _median(xs: list[float]) -> float | None:
    return statistics.median(xs) if xs else None


def _resumed(rec: dict) -> bool:
    """A checkpoint-resumed run: its wall covers only the tail since
    the checkpoint."""
    return rec.get("resumed_from_row") is not None


def ratio_evidence(rec: dict) -> bool:
    """Whether a record's wall/dispatch numbers are comparable
    full-run evidence for the ratio baselines and gates. Resumed
    tails cover only the post-checkpoint slice; ERRORED runs stop at
    the crash (a 60 s crashed wall must not become the median a
    recovered 3200 s run is judged against). Verdict/quarantine/error
    rules still apply to both in full."""
    return not _resumed(rec) and not rec.get("error")


def _verdict_char(v) -> str:
    return {True: "T", False: "F"}.get(v, "?")


def trend(records: list[dict]) -> dict[str, dict]:
    """Per-(probe, platform) trend rows: run count, last/trailing-median
    wall seconds, last verdict + the trailing verdict history string,
    dispatches/episode, wasted seconds, last git sha — what ``perf
    report`` prints and ``/perf`` renders."""
    groups: dict[str, list[dict]] = {}
    for r in records:
        groups.setdefault(group_key(r), []).append(r)
    out: dict[str, dict] = {}
    for key, recs in groups.items():
        last = recs[-1]
        # Medians over PRIOR records only — the same window gate()
        # judges, so the report's "x med" never dilutes a regression
        # with the regressing run itself. A first-ever record has no
        # trailing history (median "-"). Filter BEFORE slicing the
        # trailing window: a streak of resumed tails must not evict
        # the valid full-run baselines from the window.
        walls = [r["wall_s"] for r in recs[:-1]
                 if isinstance(r.get("wall_s"), (int, float))
                 and ratio_evidence(r)][-TRAIL:]
        dpes = [r["dispatches_per_episode"] for r in recs[:-1]
                if isinstance(r.get("dispatches_per_episode"),
                              (int, float))
                and ratio_evidence(r)][-TRAIL:]
        med = _median(walls)
        row = {"probe": last.get("probe"),
               "platform": last.get("platform"),
               "n": len(recs),
               "last_t": last.get("t"),
               "last_git": last.get("git"),
               "last_wall_s": last.get("wall_s"),
               "median_wall_s": None if med is None
               else round(med, 3),
               "last_verdict": last.get("verdict"),
               "verdicts": "".join(_verdict_char(r.get("verdict"))
                                   for r in recs[-TRAIL:]),
               "last_dispatches_per_episode":
                   last.get("dispatches_per_episode"),
               "median_dispatches_per_episode":
                   None if not dpes else round(_median(dpes), 2),
               "last_wasted_s": last.get("wasted_seconds"),
               "last_error": last.get("error"),
               "quarantine_new": last.get("quarantine_new") or []}
        if med and isinstance(last.get("wall_s"), (int, float)) \
                and ratio_evidence(last):
            row["wall_vs_median"] = round(last["wall_s"] / med, 2)
        if _resumed(last):
            row["resumed_from_row"] = last["resumed_from_row"]
        out[key] = row
    return dict(sorted(out.items()))


def render_trend(rows: dict[str, dict]) -> str:
    """The ``perf report`` table."""
    if not rows:
        return "perf ledger empty"
    lines = [f"{'probe':<28}{'plat':>6}{'n':>4}{'last s':>10}"
             f"{'med s':>10}{'x med':>7}{'d/ep':>7}{'verdicts':>10}"]
    for row in rows.values():
        lines.append(
            f"{str(row['probe'])[:27]:<28}"
            f"{str(row['platform'])[:5]:>6}"
            f"{row['n']:>4}"
            f"{_fmt(row['last_wall_s']):>10}"
            f"{_fmt(row['median_wall_s']):>10}"
            f"{_fmt(row.get('wall_vs_median')):>7}"
            f"{_fmt(row['last_dispatches_per_episode']):>7}"
            f"{row['verdicts']:>10}"
            + (f"  ! {row['last_error'][:40]}" if row.get("last_error")
               else "")
            + (f"  +quarantine:{len(row['quarantine_new'])}"
               if row.get("quarantine_new") else ""))
    return "\n".join(lines)


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.2f}"
    return str(v)


# --- diff -------------------------------------------------------------------


def diff(before: list[dict], records: list[dict]) -> list[dict]:
    """Records appended since a prior snapshot of the SAME append-only
    ledger (the ``quarantine diff`` precedent). The ledger only grows,
    so the delta is the suffix past the snapshot's length; a current
    ledger SHORTER than the snapshot means it was cleared/rotated —
    report everything current rather than a bogus empty delta."""
    if len(records) < len(before):
        return list(records)
    return records[len(before):]


def render_diff(new: list[dict], trend_rows: dict | None = None) -> str:
    """One line per new record, each against its trend row's median
    (what ``make probe-config5`` prints after the quarantine delta)."""
    if not new:
        return "perf delta: none"
    lines = [f"perf delta: {len(new)} new record(s)"]
    for r in new:
        row = (trend_rows or {}).get(group_key(r)) or {}
        med = row.get("median_wall_s")
        vs = ""
        if med and isinstance(r.get("wall_s"), (int, float)):
            vs = f"  ({r['wall_s'] / med:.2f}x trailing median)"
        lines.append(
            f"  {r.get('t')}  {r.get('probe')}  "
            f"[{r.get('platform')}]  wall {_fmt(r.get('wall_s'))} s  "
            f"verdict {r.get('verdict')}{vs}"
            + (f"  ERROR {str(r.get('error'))[:60]}"
               if r.get("error") else "")
            + (f"  +quarantine {len(r['quarantine_new'])}"
               if r.get("quarantine_new") else ""))
    return "\n".join(lines)


# --- gate -------------------------------------------------------------------


def gate(records: list[dict], probe: str | None = None,
         frac: float | None = None) -> list[dict]:
    """The regression sentinel: judge the LAST record of each (probe,
    platform) group against its trailing history. Findings (empty =
    pass):

    - ``verdict-flip`` — the verdict differs from the previous
      same-shape record's (hard fail: an engine change flipped or
      degraded a decided history, incl. ok -> error). A clean flip
      back TO True is RECOVERY, not a flip — the flip away already
      fired and the still-* rules kept the row red since;
    - ``error-appeared`` — the verdict matches but the run newly
      carries an error where its predecessor was clean (the bench
      headline's crash-free FALLBACK records verdict True + the
      crashed-op failure — same verdict, degraded run);
    - ``still-erroring`` — consecutive errored runs with the same
      verdict: the gate is LEVEL-triggered on errors, so a
      persistently broken probe stays red every run until it
      recovers (not just on the first failure);
    - ``still-flipped`` — the clean twin: a verdict stuck non-True
      after an established True baseline (every producer's True
      means its contract held) stays red until it recovers;
    - ``wall-regression`` — wall_s > frac x trailing median of the
      prior ``TRAIL`` runs (needs ``MIN_TREND`` priors);
    - ``new-quarantine`` — the run recorded new quarantine-ledger
      entries (an engine change newly faulted a shape);
    - ``dispatch-growth`` — dispatches/episode > frac x trailing
      median (the kill-the-tunnel metric regressing).

    Checkpoint-RESUMED records (``resumed_from_row``) are excluded
    from the wall/dispatch baselines and never judged by the ratio
    rules — their numbers cover only the tail since the checkpoint;
    verdict and quarantine rules still apply to them in full.
    """
    frac = gate_frac() if frac is None else frac
    groups: dict[str, list[dict]] = {}
    for r in records:
        if probe is not None and r.get("probe") != probe:
            continue
        groups.setdefault(group_key(r), []).append(r)
    findings: list[dict] = []

    def hit(rule, rec, detail):
        findings.append({"rule": rule, "probe": rec.get("probe"),
                         "platform": rec.get("platform"),
                         "t": rec.get("t"), "git": rec.get("git"),
                         "detail": detail})

    for key, recs in sorted(groups.items()):
        last = recs[-1]
        prior = recs[:-1]
        # verdict flip: vs the most recent prior record. An errored
        # run counts as verdict None — ok -> error IS a flip.
        if prior:
            pv, lv = prior[-1].get("verdict"), last.get("verdict")
            verdict_handled = False
            if pv != lv:
                # RECOVERY is not a flip. Two recovery shapes: (a) a
                # clean flip TO True — every producer's True means
                # its contract held, and the flip AWAY already fired
                # (with still-flipped/still-erroring keeping the row
                # red since), so the fix run must not fail CI again;
                # (b) a clean run matching the last clean verdict
                # before an errored streak (or a new tag whose only
                # priors errored) re-establishes that baseline.
                recovery = lv is True and not last.get("error")
                if not recovery and not last.get("error") \
                        and prior[-1].get("error"):
                    clean = [r for r in prior if not r.get("error")]
                    recovery = not clean \
                        or clean[-1].get("verdict") == lv
                if not recovery:
                    verdict_handled = True
                    hit("verdict-flip", last,
                        f"verdict {pv!r} -> {lv!r}"
                        + (f" (error: "
                           f"{str(last.get('error'))[:80]})"
                           if last.get("error") else ""))
            elif last.get("error") and not prior[-1].get("error"):
                verdict_handled = True
                hit("error-appeared", last,
                    f"verdict unchanged ({lv!r}) but the run newly "
                    f"carries an error: "
                    f"{str(last.get('error'))[:100]}")
            elif last.get("error") and prior[-1].get("error"):
                # LEVEL-triggered, not edge-triggered: a persistently
                # failing probe must stay red on every run until it
                # recovers — the first errored run fired
                # verdict-flip/error-appeared, and without this rule
                # the second identical failure would read as PASS.
                verdict_handled = True
                hit("still-erroring", last,
                    f"run still erroring (verdict {lv!r}): "
                    f"{str(last.get('error'))[:100]}")
            if not verdict_handled and lv is not True and any(
                    r.get("verdict") is True for r in prior):
                # The clean twin of still-erroring: every producer's
                # True means its contract held, so a verdict stuck
                # non-True after an established True baseline is a
                # PERSISTING soundness regression — red on EVERY run
                # until recovery, including one that merely cleared
                # its error while staying non-True (a recovery-(b)
                # pass above must not skip this rule).
                hit("still-flipped", last,
                    f"verdict still {lv!r} after an established "
                    f"True baseline")
        if last.get("quarantine_new"):
            hit("new-quarantine", last,
                f"{len(last['quarantine_new'])} newly faulted "
                f"shape(s): "
                + ", ".join(last["quarantine_new"][:4]))
        if not ratio_evidence(last):
            # A resumed run's numbers cover only the tail and an
            # errored run's stop at the crash — meaningless against
            # full-run baselines in either direction. The flip/error/
            # quarantine rules above already ran.
            continue
        # Filter BEFORE slicing (the trend() rule): a streak of
        # resumed tails must not evict valid baselines and silently
        # disable the ratio gates for a resume-heavy probe.
        walls = [r["wall_s"] for r in prior
                 if isinstance(r.get("wall_s"), (int, float))
                 and ratio_evidence(r)][-TRAIL:]
        if len(walls) >= MIN_TREND \
                and isinstance(last.get("wall_s"), (int, float)):
            med = _median(walls)
            if med and last["wall_s"] > frac * med:
                hit("wall-regression", last,
                    f"wall {last['wall_s']} s > {frac}x trailing "
                    f"median {med:.3f} s ({last['wall_s'] / med:.2f}x)")
        dpes = [r["dispatches_per_episode"] for r in prior
                if isinstance(r.get("dispatches_per_episode"),
                              (int, float))
                and ratio_evidence(r)][-TRAIL:]
        if len(dpes) >= MIN_TREND and isinstance(
                last.get("dispatches_per_episode"), (int, float)):
            med = _median(dpes)
            if med and last["dispatches_per_episode"] > frac * med:
                hit("dispatch-growth", last,
                    f"dispatches/episode "
                    f"{last['dispatches_per_episode']} > {frac}x "
                    f"trailing median {med:.2f}")
    return findings


def render_gate(findings: list[dict]) -> str:
    if not findings:
        return "perf gate: PASS"
    lines = [f"perf gate: FAIL ({len(findings)} finding(s))"]
    for f in findings:
        lines.append(f"  [{f['rule']}] {f['probe']} "
                     f"[{f['platform']}] {f['t']}: {f['detail']}")
    return "\n".join(lines)
