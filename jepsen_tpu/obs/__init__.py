"""Flight recorder: span tracing, metrics registry, time attribution.

The checker stack's observability layer (doc/observability.md). Three
pieces, all chip-free and jax-free at import time (web.py and the CLI
load them without dragging a backend in):

- :mod:`jepsen_tpu.obs.trace` — the thread-safe span tracer threaded
  through every engine dispatch choke point (``supervise.call``), the
  chunk/host-row/spike executors, the checker daemon, and the txn
  tiers. ``JEPSEN_TPU_TRACE=1`` turns it on; off, ``span()`` returns a
  shared null object and records nothing.
- :mod:`jepsen_tpu.obs.metrics` — the typed metrics registry the
  engines' stats dicts (host-stats / mesh-stats / service stats / txn
  stats) register into as named views, plus run-progress gauges and
  the event feed behind ``web.py /run`` and ``cli.py host-stats``.
- :mod:`jepsen_tpu.obs.report` — time attribution: the
  where-did-the-time-go table (``cli.py trace report``), the
  Chrome/Perfetto trace-event export (``cli.py trace export``), and
  the compact summary bench probes attach to their JSON artifacts.
- :mod:`jepsen_tpu.obs.ledger` — the CROSS-run perf ledger
  (``JEPSEN_TPU_PERF_LEDGER``): every bench probe rung, probe-config5,
  and chip-free smoke appends one record (git sha, platform, env-knob
  fingerprint, wall/verdict/host-stats/trace/quarantine delta);
  ``cli.py perf report|diff|gate`` and ``web.py /perf`` read it, and
  ``perf gate`` is the CI-consumable regression sentinel.

The tracer OBSERVES; it never routes — soundness-critical paths are
untouched whether tracing is on or off.
"""

from jepsen_tpu.obs import ledger, metrics, report, trace  # noqa: F401
from jepsen_tpu.obs.metrics import REGISTRY, load_json_snapshot  # noqa: F401
from jepsen_tpu.obs.trace import enabled, span, tail_note  # noqa: F401
