"""`make perf-smoke`: record -> report -> gate, with seeded regressions.

The chip-free habit for the cross-run perf ledger (obs/ledger,
doc/observability.md § Perf ledger), in the serve/txn/trace/stream
smoke mold: a FRESH-process proof on the forced CPU mesh that

- a real CPU-mesh check records to the ledger (git sha + env
  fingerprint stamped, index written),
- ``cli.py perf report`` renders its trend row,
- ``cli.py perf gate`` PASSES on the healthy history, and
- a seeded injected regression is CAUGHT: both the wall-time case
  (one run at many x the trailing median) and the verdict-flip case
  (True -> False) exit nonzero with the right rule named.

The seeded regressions go into a THROWAWAY ledger
(``.jax_cache/perf_smoke.ledger.jsonl``, truncated per run) so
fabricated evidence never pollutes the real trajectory — the
quarantine-redirect precedent in service/chaos.py. The smoke's own
run is recorded to the REAL ledger like every other smoke. Prints one
JSON result line and exits 0/1 — timeout-guarded by the Makefile.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> int:
    t_start = time.time()
    # CPU mesh BEFORE any jax backend init (CLAUDE.md: the TPU plugin
    # force-selects its platform; the smoke must never take the chip).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from jepsen_tpu import cli, util, web
    from jepsen_tpu.lin import cpu, device_check_packed, prepare, synth
    from jepsen_tpu import models as m
    from jepsen_tpu.obs import ledger

    util.enable_compile_cache()
    # The real ledger path (for the smoke's own producer record),
    # resolved BEFORE the throwaway override below. The throwaway is
    # cache_dir-anchored like every on-disk artifact, so running the
    # smoke from any cwd cleans up the same file.
    real_ledger = ledger.ledger_path()
    smoke_ledger = os.path.join(util.cache_dir(),
                                "perf_smoke.ledger.jsonl")
    for f in (smoke_ledger, smoke_ledger + ".index.json"):
        try:
            os.remove(f)
        except OSError:
            pass
    # Every record the smoke fabricates lands in the throwaway file;
    # env_fp still stamps honestly (it reads the environment).
    os.environ["JEPSEN_TPU_PERF_LEDGER"] = smoke_ledger

    out: dict = {"ledger": smoke_ledger, "checks": []}
    ok = True

    # --- a real CPU-mesh check, recorded -----------------------------------
    h = synth.generate_register_history(
        300, concurrency=5, seed=7, value_range=5, crash_prob=0.01,
        max_crashes=3)
    p = prepare.prepare(m.cas_register(), h)
    want = cpu.check_packed(p)["valid?"]
    device_check_packed(p)                      # warm/compile
    walls = []
    for _ in range(3):
        t0 = time.time()
        r = device_check_packed(p)
        walls.append(time.time() - t0)
        rec = ledger.record("cpu-mesh-check", kind="smoke",
                            wall_s=walls[-1], verdict=r.get("valid?"))
        ok = ok and rec is not None and r.get("valid?") == want
    out["checks"].append({"leg": "record", "want": want,
                          "got": r.get("valid?"),
                          "walls": [round(w, 3) for w in walls],
                          "git": (rec or {}).get("git"),
                          "env_fp": (rec or {}).get("env_fp")})
    ok = ok and os.path.exists(smoke_ledger) \
        and os.path.exists(smoke_ledger + ".index.json") \
        and bool((rec or {}).get("env_fp"))

    # --- report renders the trend row ---------------------------------------
    rows = ledger.trend(ledger.load(smoke_ledger))
    report_rc = cli.run(cli.standard_commands(["perf"]),
                        ["perf", "report", "--ledger", smoke_ledger])
    out["checks"].append({"leg": "report", "rc": report_rc,
                          "rows": sorted(rows)})
    ok = ok and report_rc == 0 and any(
        row["probe"] == "cpu-mesh-check" for row in rows.values())

    # --- gate passes on the healthy history ---------------------------------
    # Loose --frac for THIS leg only: the real walls are milliseconds,
    # where ordinary scheduler/GC jitter on shared hardware can exceed
    # 1.5x run to run — a healthy-checkout smoke must not flake on
    # noise. The verdict/quarantine/error rules still run at full
    # strength, and the seeded legs below use the real default
    # threshold against a 10x spike.
    healthy_rc = cli.run(cli.standard_commands(["perf"]),
                         ["perf", "gate", "--ledger", smoke_ledger,
                          "--frac", "10"])
    out["checks"].append({"leg": "gate-healthy", "rc": healthy_rc})
    ok = ok and healthy_rc == 0

    # --- pack-seconds fields round-trip (ISSUE 16) --------------------------
    # The ``pack`` sub-dict bench's _probe_main forwards (pack wall +
    # packer mode) must survive the record -> load round trip verbatim
    # AND stay inert to the gate rules: it is observability the report
    # trends, never evidence the gate fires on.
    med = sorted(walls)[1]
    pack = {"prepare_s": 0.123, "incr_s": 0.0, "mode": "vec"}
    ledger.record("cpu-mesh-check", kind="smoke", wall_s=med,
                  verdict=want, extra={"pack": pack})
    recs = [r for r in ledger.load(smoke_ledger)
            if r.get("probe") == "cpu-mesh-check" and "pack" in r]
    pack_rc = cli.run(cli.standard_commands(["perf"]),
                      ["perf", "gate", "--ledger", smoke_ledger,
                       "--frac", "10"])
    out["checks"].append({"leg": "pack-roundtrip", "rc": pack_rc,
                          "pack": recs[-1].get("pack") if recs
                          else None})
    ok = ok and bool(recs) and recs[-1]["pack"] == pack \
        and pack_rc == 0

    # --- seeded WALL regression must be caught ------------------------------
    # The seeded legs PIN --frac at the shipped default: an exported
    # JEPSEN_TPU_PERF_GATE_FRAC tuned for a noisy tunnel (doc/env.md
    # invites it) must not make the 10x spike pass and fail the smoke
    # on a healthy checkout.
    ledger.record("cpu-mesh-check", kind="smoke",
                  wall_s=med * 10, verdict=want)
    findings = ledger.gate(ledger.load(smoke_ledger), frac=1.5)
    wall_rc = cli.run(cli.standard_commands(["perf"]),
                      ["perf", "gate", "--ledger", smoke_ledger,
                       "--frac", "1.5"])
    out["checks"].append({"leg": "gate-wall-regression", "rc": wall_rc,
                          "rules": sorted(f["rule"] for f in findings)})
    ok = ok and wall_rc != 0 \
        and any(f["rule"] == "wall-regression" for f in findings)

    # --- seeded VERDICT FLIP must be caught ---------------------------------
    ledger.record("cpu-mesh-check", kind="smoke", wall_s=med,
                  verdict=not want)
    findings = ledger.gate(ledger.load(smoke_ledger), frac=1.5)
    flip_rc = cli.run(cli.standard_commands(["perf"]),
                      ["perf", "gate", "--ledger", smoke_ledger,
                       "--frac", "1.5"])
    out["checks"].append({"leg": "gate-verdict-flip", "rc": flip_rc,
                          "rules": sorted(f["rule"] for f in findings)})
    ok = ok and flip_rc != 0 \
        and any(f["rule"] == "verdict-flip" for f in findings)

    # --- /perf page renders the trajectory ----------------------------------
    html = web.perf_html(smoke_ledger)
    out["checks"].append({"leg": "/perf", "bytes": len(html)})
    ok = ok and "perf ledger" in html and "cpu-mesh-check" in html

    out["ok"] = bool(ok)
    # The smoke's own producer record goes to the REAL ledger (the
    # other smokes' habit) — never the throwaway one it judged.
    if real_ledger is not None:
        ledger.record("perf-smoke", path=real_ledger, kind="smoke",
                      wall_s=time.time() - t_start, verdict=bool(ok))
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
