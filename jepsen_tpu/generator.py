"""Operation generator DSL.

Composable, stateful op sources — a re-design of the reference's
`jepsen/src/jepsen/generator.clj` (457 LoC): "Generates operations for a
test... Every object may act as a generator, and constantly yields itself.
Big ol box of monads, really."

The protocol is a single function ``op(gen, test, process)``
(generator.clj:22-23) where ``gen`` may be:

- ``None``        — terminates (yields None forever)
- an :class:`Op` or dict — constantly yields itself
- a callable      — called as ``f(test, process)`` or ``f()``
- a :class:`Generator` — dispatches to its ``op`` method

Thread routing (``on``/``reserve``/``nemesis``/``clients``) rebinds the
dynamically-scoped thread set exactly like the reference's ``*threads*``
var (generator.clj:40-55), here a context variable bound per worker thread.
Synchronization combinators (``synchronize``/``phases``/``barrier``) block
threads on a shared barrier (generator.clj:402-424).
"""

from __future__ import annotations

import contextvars
import random as _random
import threading
import time as _time
from typing import Any, Callable, Iterable

from jepsen_tpu.history import Op, op as _as_op

_threads_var: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "jepsen_threads", default=())


def current_threads() -> tuple:
    """The ordered collection of threads executing the current generator:
    'nemesis' plus 0..concurrency-1 (generator.clj:40-46)."""
    return _threads_var.get()


def _sort_threads(threads: Iterable) -> tuple:
    """Integers first in order, then named threads — knossos
    sort-processes order."""
    ts = list(threads)
    ints = sorted(t for t in ts if isinstance(t, int))
    others = [t for t in ts if not isinstance(t, int)]
    return tuple(ints + others)


class with_threads:
    """Context manager binding the thread set (generator.clj:48-55).
    Asserts the threads are sorted, like the reference."""

    def __init__(self, threads: Iterable):
        self.threads = tuple(threads)
        assert self.threads == _sort_threads(self.threads), \
            f"threads not sorted: {self.threads}"

    def __enter__(self):
        self._token = _threads_var.set(self.threads)
        return self.threads

    def __exit__(self, *exc):
        _threads_var.reset(self._token)
        return False


def process_to_thread(test, process):
    """process mod concurrency, or the process itself for named threads like
    'nemesis' (generator.clj:57-62)."""
    if isinstance(process, int):
        return process % test["concurrency"]
    return process


def process_to_node(test, process):
    """The node this process is likely talking to (generator.clj:64-71)."""
    thread = process_to_thread(test, process)
    if isinstance(thread, int):
        nodes = test["nodes"]
        return nodes[thread % len(nodes)]
    return None


class Generator:
    def op(self, test, process):
        raise NotImplementedError


def op(gen, test, process):
    """Yield an operation from any generator-like object (the open protocol
    of generator.clj:25-38)."""
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.op(test, process)
    if isinstance(gen, (Op, dict)):
        return gen
    if callable(gen):
        try:
            return gen(test, process)
        except TypeError:
            return gen()
    return gen


def op_and_validate(gen, test, process):
    """Ensure the generator produced an op map or None
    (generator.clj:446-457)."""
    o = op(gen, test, process)
    if o is not None and not isinstance(o, (Op, dict)):
        raise AssertionError(
            f"Expected an operation map from {gen!r}, got {o!r} instead.")
    return o


class _Fn(Generator):
    def __init__(self, fn):
        self.fn = fn

    def op(self, test, process):
        return self.fn(test, process)


def gen(fn) -> Generator:
    """Wrap a 2-arg function as a generator."""
    return _Fn(fn)


void = gen(lambda test, process: None)
"""A generator which terminates immediately (generator.clj:73-76)."""


def sleep_til_nanos(t: int) -> None:
    """High-resolution sleep until monotonic nanos t (generator.clj:78-82)."""
    while _time.monotonic_ns() + 10_000 < t:
        _time.sleep(max(0.0, (t - _time.monotonic_ns()) / 1e9))


def delay_fn(f: Callable[[], float], source) -> Generator:
    """Every op from the underlying generator takes f() seconds longer
    (generator.clj:89-95)."""

    def go(test, process):
        _time.sleep(f())
        return op(source, test, process)

    return gen(go)


def delay(dt: float, source) -> Generator:
    """Every op takes dt seconds to return (generator.clj:97-100)."""
    return delay_fn(lambda: dt, source)


def next_tick_nanos(anchor: int, dt: int, now: int | None = None) -> int:
    """Next tick after `now` separated from anchor by a multiple of dt
    (generator.clj:102-110)."""
    if now is None:
        now = _time.monotonic_ns()
    return now + (dt - (now - anchor) % dt)


def delay_til(dt: float, source, precache: bool = True) -> Generator:
    """Emit ops as close as possible to multiples of dt seconds from an
    epoch — useful for triggering race conditions (generator.clj:112-135)."""
    anchor = _time.monotonic_ns()
    dtn = int(dt * 1e9)

    if precache:
        def go(test, process):
            o = op(source, test, process)
            sleep_til_nanos(next_tick_nanos(anchor, dtn))
            return o
    else:
        def go(test, process):
            sleep_til_nanos(next_tick_nanos(anchor, dtn))
            return op(source, test, process)

    return gen(go)


def stagger(dt: float, source) -> Generator:
    """Uniform random delay, mean dt, range [0, 2dt)
    (generator.clj:137-141)."""
    return delay_fn(lambda: _random.uniform(0, 2 * dt), source)


def sleep(dt: float) -> Generator:
    """Takes dt seconds, always produces None (generator.clj:143-146)."""
    return delay(dt, void)


def once(source) -> Generator:
    """Invoke the underlying generator only once (generator.clj:148-156)."""
    state = {"emitted": False}
    lock = threading.Lock()

    def go(test, process):
        with lock:
            if state["emitted"]:
                return None
            state["emitted"] = True
        return op(source, test, process)

    return gen(go)


def log_every(msg: str) -> Generator:
    """Log a message every time invoked, yield None
    (generator.clj:158-164)."""
    import logging

    def go(test, process):
        logging.getLogger("jepsen").info(msg)
        return None

    return gen(go)


def log(msg: str) -> Generator:
    """Log a message once, yield None (generator.clj:166-169)."""
    return once(log_every(msg))


def each(gen_fn: Callable[[], Any]) -> Generator:
    """A fresh copy of the underlying generator per process
    (generator.clj:171-193). Takes a zero-arg factory."""
    gens: dict = {}
    lock = threading.Lock()

    def go(test, process):
        with lock:
            if process not in gens:
                gens[process] = gen_fn()
            g = gens[process]
        return op(g, test, process)

    return gen(go)


def seq(coll: Iterable) -> Generator:
    """One op from the first element, then one from the second, etc.; an
    element yielding None advances immediately; None once the collection is
    exhausted (generator.clj:195-206 — the reference pops one element per
    call, so each element emits at most one op)."""
    it = iter(coll)
    lock = threading.Lock()

    def go(test, process):
        while True:
            with lock:
                try:
                    g_ = next(it)
                except StopIteration:
                    return None
            o = op(g_, test, process)
            if o is not None:
                return o

    return gen(go)


def _cycle(xs):
    while True:
        yield from xs


def start_stop(t1: float, t2: float) -> Generator:
    """Emit {:info :start} after t1 seconds, {:info :stop} after t2, repeat
    (generator.clj:208-215). The canonical nemesis schedule."""
    return seq(_start_stop_iter(t1, t2))


def _start_stop_iter(t1, t2):
    while True:
        yield sleep(t1)
        yield Op("info", "start")
        yield sleep(t2)
        yield Op("info", "stop")


def mix(gens: Iterable) -> Generator:
    """Uniform random mixture of generators (generator.clj:217-224)."""
    gens = list(gens)

    def go(test, process):
        return op(_random.choice(gens), test, process)

    return gen(go)


def cas(value_range: int = 5) -> Generator:
    """Random read/write/cas ops over a small int field
    (generator.clj:226-239)."""

    def go(test, process):
        r = _random.random()
        if r > 0.66:
            return Op("invoke", "read", None)
        if r > 0.33:
            return Op("invoke", "write", _random.randrange(value_range))
        return Op("invoke", "cas", [_random.randrange(value_range),
                                    _random.randrange(value_range)])

    return gen(go)


def queue_gen() -> Generator:
    """Random enqueue (consecutive ints) / dequeue mix
    (generator.clj:241-252)."""
    counter = {"i": -1}
    lock = threading.Lock()

    def go(test, process):
        if _random.random() > 0.5:
            with lock:
                counter["i"] += 1
                return Op("invoke", "enqueue", counter["i"])
        return Op("invoke", "dequeue", None)

    return gen(go)


def drain_queue(source) -> Generator:
    """Track enqueue/dequeue balance; when the source is exhausted, emit
    enough dequeues to drain every attempted enqueue
    (generator.clj:254-269)."""
    state = {"outstanding": 0}
    lock = threading.Lock()

    def go(test, process):
        o = op(source, test, process)
        if o is not None:
            if o.get("f") == "enqueue":
                with lock:
                    state["outstanding"] += 1
            return o
        with lock:
            state["outstanding"] -= 1
            remaining = state["outstanding"]
        if remaining >= 0:
            return Op("invoke", "dequeue", None)
        return None

    return gen(go)


def limit(n: int, source) -> Generator:
    """Only produce n operations (generator.clj:271-278)."""
    state = {"life": n + 1}
    lock = threading.Lock()

    def go(test, process):
        with lock:
            state["life"] -= 1
            alive = state["life"] > 0
        if alive:
            return op(source, test, process)
        return None

    return gen(go)


def time_limit(dt: float, source) -> Generator:
    """Yield ops until dt seconds have elapsed (generator.clj:280-291)."""
    state = {"deadline": None}
    lock = threading.Lock()

    def go(test, process):
        with lock:
            if state["deadline"] is None:
                state["deadline"] = _time.monotonic() + dt
        if _time.monotonic() <= state["deadline"]:
            return op(source, test, process)
        return None

    return gen(go)


def filter_gen(f: Callable, source) -> Generator:
    """Only ops satisfying f(op) (generator.clj:293-303)."""

    def go(test, process):
        while True:
            o = op(source, test, process)
            if o is None:
                return None
            if f(o):
                return o

    return gen(go)


def on(f: Callable, source) -> Generator:
    """Forward ops iff f(thread) is truthy; rebinds the thread set
    (generator.clj:305-313)."""

    def go(test, process):
        thread = process_to_thread(test, process)
        if not f(thread):
            return None
        sub = tuple(t for t in current_threads() if f(t))
        with with_threads(sub):
            return op(source, test, process)

    return gen(go)


def reserve(*args) -> Generator:
    """(reserve(5, write_gen, 10, cas_gen, read_gen)): first 5 threads use
    write_gen, next 10 cas_gen, the rest the default
    (generator.clj:315-358). Rebinds the thread set per range."""
    if len(args) % 2 != 1:
        raise ValueError("reserve takes count/gen pairs + a default gen")
    pairs = list(zip(args[:-1:2], args[1:-1:2]))
    default = args[-1]
    ranges = []
    n = 0
    for count, g in pairs:
        ranges.append((n, n + count, g))
        n += count

    def go(test, process):
        threads = list(current_threads())
        thread = process_to_thread(test, process)
        chosen = None
        for lower, upper, g in ranges:
            if upper <= len(threads) and \
                    threads.index(thread) < upper:
                chosen = (lower, upper, g)
                break
        if chosen is None:
            lower = ranges[-1][1] if ranges else 0
            chosen = (lower, len(threads), default)
        lo, hi, g = chosen
        with with_threads(tuple(threads[lo:hi])):
            return op(g, test, process)

    return gen(go)


def concat(*sources) -> Generator:
    """First non-None op from the sources, in order
    (generator.clj:360-370)."""

    def go(test, process):
        for source in sources:
            o = op(source, test, process)
            if o is not None:
                return o
        return None

    return gen(go)


def nemesis(nemesis_gen, client_gen=None) -> Generator:
    """Route the 'nemesis' process to nemesis_gen, others to client_gen
    (generator.clj:372-380)."""
    if client_gen is None:
        return on(lambda t: t == "nemesis", nemesis_gen)
    return concat(on(lambda t: t == "nemesis", nemesis_gen),
                  on(lambda t: t != "nemesis", client_gen))


def clients(client_gen) -> Generator:
    """Execute only on client threads (generator.clj:382-385)."""
    return on(lambda t: t != "nemesis", client_gen)


def await_fn(f: Callable, source=None) -> Generator:
    """Block until f() returns (invoked once), then proceed with source
    (generator.clj:387-400)."""
    state = {"waiting": True}
    lock = threading.Lock()

    def go(test, process):
        if state["waiting"]:
            with lock:
                if state["waiting"]:
                    f()
                    state["waiting"] = False
        return op(source, test, process)

    return gen(go)


def synchronize(source) -> Generator:
    """Block until every thread in the current thread set is awaiting an op
    from this generator, then proceed; synchronizes once
    (generator.clj:402-418)."""
    state: dict = {"barrier": None, "clear": False}
    lock = threading.Lock()

    def go(test, process):
        if not state["clear"]:
            with lock:
                if state["barrier"] is None and not state["clear"]:
                    n = len(current_threads())

                    def clear():
                        state["clear"] = True

                    state["barrier"] = threading.Barrier(n, action=clear)
                barrier = state["barrier"]
            if barrier is not None and not state["clear"]:
                try:
                    barrier.wait()
                except threading.BrokenBarrierError:
                    pass
        return op(source, test, process)

    return gen(go)


def phases(*generators) -> Generator:
    """Like concat, but all threads must finish each generator before moving
    on (generator.clj:420-424)."""
    return concat(*[synchronize(g) for g in generators])


def then(a, b) -> Generator:
    """Generator b, synchronize, then generator a — backwards so it reads
    well in pipelines (generator.clj:426-430)."""
    return concat(b, synchronize(a))


def singlethreaded(source) -> Generator:
    """Exclusive lock around the underlying generator
    (generator.clj:432-439)."""
    lock = threading.Lock()

    def go(test, process):
        with lock:
            return op(source, test, process)

    return gen(go)


def barrier(source) -> Generator:
    """When the generator completes, synchronize, then yield None
    (generator.clj:441-444)."""
    return then(void, source)
