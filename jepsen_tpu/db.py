"""DB lifecycle protocol: install/teardown the system under test.

Re-design of `jepsen/src/jepsen/db.clj` (25 LoC): DB/Primary/LogFiles
protocols (db.clj:4-12) and ``cycle`` = teardown then setup (db.clj:20-25).
"""

from __future__ import annotations


class DB:
    def setup(self, test, node) -> None:
        """Install and start the database on node (db.clj:5-6)."""

    def teardown(self, test, node) -> None:
        """Remove the database from node (db.clj:7-8)."""


class Primary:
    """Optional mixin: databases with a distinguished primary
    (db.clj:9-10)."""

    def setup_primary(self, test, node) -> None:
        """Perform primary-specific setup on the first node."""


class LogFiles:
    """Optional mixin: log file enumeration for download (db.clj:11-12)."""

    def log_files(self, test, node) -> list[str]:
        return []


class NoopDB(DB):
    """Does nothing (db.clj:14-18)."""


noop = NoopDB()


def cycle(db: DB, test, node) -> None:
    """Tear down, then set up (db.clj:20-25)."""
    db.teardown(test, node)
    db.setup(test, node)
