"""Durable request journal: the daemon's crash-survival record.

The checker daemon (daemon.py) is itself a distributed-systems
participant: clients hand it histories and expect a verdict, and the
wire contract (protocol.py) already says a lost reply is INDETERMINATE
— the daemon may have decided. What a daemon CRASH must not do is
silently drop admitted work: the journal makes every admitted request
durable before it is decided, and a restarted daemon re-decides
everything unsettled — the PR 5 checkpoint/ledger machinery promoted
from one engine run to the whole service.

Design (the trace-spill + quarantine-ledger patterns combined):

- **Append-only JSONL** (``JEPSEN_TPU_SERVICE_JOURNAL``): one record
  per line, encoded with :mod:`jepsen_tpu.codec` so histories
  round-trip exactly (tuples/sets survive). Record kinds:

  - ``{"kind": "check", "seq": N, "fp": F, "model": M, "history":
    [...]}`` — an admitted check (``txn-check`` twins carry the txn
    params instead of a model). Appended (and flushed) BEFORE the
    request enters the queue, so a crash after admission can never
    lose it.
  - ``{"kind": "settle", "seq": N, "fp": F, "verdict": V, "result":
    {...}}`` — the answer. The settle record IS the durable reply: a
    client that lost its connection (or never reconnects) can read the
    verdict here, and the restart-recovery test asserts these against
    the CPU oracle.
  - ``{"kind": "stream-open", "seq": N, "sid": S, "model": M}`` /
    ``{"kind": "stream-append", "seq": N, "sid": S, "ops": [...]}`` /
    ``{"kind": "stream-close", "seq": N, "sid": S, "how": ...}`` — a
    daemon stream session's lifecycle. A crashed session's carried
    frontier survives via its per-sid ``JEPSEN_TPU_STREAM_CKPT``
    checkpoint; re-feeding the journaled appends fast-forwards to it
    (the settled-prefix fingerprint gate, stream/session.py).

- **Torn-tail-tolerant replay**: a SIGKILL can tear the last line;
  ``load()`` skips unparseable lines (counting them) exactly like the
  trace spill reader — a torn tail costs that one record, never the
  journal.

- **Atomic index** (``<path>.index.json``, ``util.write_json_atomic``):
  a compact summary (next seq, unsettled depth, settled count, replay
  counter) written atomically so monitoring (``cli.py journal list``,
  the ``/service`` page) reads a consistent snapshot without parsing
  the whole JSONL. The index is derived state — replay trusts only
  the JSONL.

- **gc** rewrites the file keeping unsettled checks and open stream
  sessions (atomic tmp+replace), dropping settled pairs — the journal
  stays O(in-flight), not O(history of the service).

Thread-safety: one lock around the append path (handler threads,
worker threads, and the supervisor all write). Fsync is NOT issued per
line — the flush gives os-crash durability for process kills (the
failure mode the fleet story defends against); powerfail durability
would need fsync and is not worth the per-request latency here.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Iterable

from jepsen_tpu import codec, util

JOURNAL_VERSION = 1


def journal_path() -> str | None:
    """``JEPSEN_TPU_SERVICE_JOURNAL``: the journal file; unset/empty or
    ``0`` disables journaling entirely (the pre-fleet daemon)."""
    env = os.environ.get("JEPSEN_TPU_SERVICE_JOURNAL", "")
    if not env or env == "0":
        return None
    return env


class Journal:
    """One daemon's request journal. All methods are thread-safe."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = None
        self._next_seq = 1
        self._unsettled: dict[int, dict] = {}   # seq -> admit record
        self._settled = 0
        self._torn = 0
        self._frozen = False    # crash(): drop writes, never reopen
        self.replays = 0        # entries re-decided by a restart
        self._streams: dict[str, dict] = {}     # sid -> session record
        self._results: dict[str, dict] = {}     # fp -> latest settle
        self._recover()

    # --- load / recovery ----------------------------------------------------

    def _recover(self) -> None:
        """Rebuild in-memory state from the JSONL (startup)."""
        for rec in self.load():
            self._apply(rec)

    def _apply(self, rec: dict) -> None:
        seq = rec.get("seq")
        if isinstance(seq, int):
            self._next_seq = max(self._next_seq, seq + 1)
        kind = rec.get("kind")
        if kind in ("check", "txn-check"):
            self._unsettled[seq] = rec
        elif kind == "settle":
            if self._unsettled.pop(rec.get("of", seq), None) is not None:
                self._settled += 1
            fp = rec.get("fp")
            if fp:
                # Latest settle wins (a replayed request re-settles
                # the same fp): the record result-fetch serves.
                self._results[fp] = {"of": rec.get("of", seq),
                                     "verdict": rec.get("verdict"),
                                     "result": rec.get("result")}
        elif kind == "stream-open":
            self._streams[rec["sid"]] = {"model": rec.get("model"),
                                         "appends": [], "closed": False,
                                         "seq": seq}
        elif kind == "stream-append":
            s = self._streams.get(rec.get("sid"))
            if s is not None and not s["closed"]:
                s["appends"].append(rec.get("ops") or [])
        elif kind == "stream-close":
            s = self._streams.get(rec.get("sid"))
            if s is not None:
                s["closed"] = True

    def load(self) -> list[dict]:
        """Every parseable record, in order (torn-tail tolerant: an
        unparseable line — the SIGKILL-torn tail — is skipped and
        counted, like the trace-spill reader)."""
        out: list[dict] = []
        try:
            with open(self.path, "rb") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = codec.decode(line)
                    except Exception:  # noqa: BLE001 - torn tail
                        self._torn += 1
                        continue
                    if isinstance(rec, dict):
                        out.append(rec)
        except OSError:
            pass
        return out

    # --- writing ------------------------------------------------------------

    def _append(self, rec: dict) -> int:
        with self._lock:
            if self._frozen:
                # A frozen (crashed) journal drops writes instead of
                # lazily reopening the file: an in-flight worker's
                # settle landing AFTER the simulated SIGKILL would be
                # a record a real kill could never produce.
                return -1
            seq = rec.get("seq")
            if seq is None:
                seq = self._next_seq
                rec = {**rec, "seq": seq}
            self._next_seq = max(self._next_seq, seq + 1)
            if self._fh is not None:
                # A compaction (`cli.py journal gc`) in ANOTHER
                # process swaps the inode under our append handle;
                # writing on would scribble on an unlinked file and
                # silently lose every later admit/settle. Detect and
                # reopen.
                try:
                    if os.stat(self.path).st_ino \
                            != os.fstat(self._fh.fileno()).st_ino:
                        self._fh.close()
                        self._fh = None
                except OSError:
                    self._fh.close()
                    self._fh = None
            if self._fh is None:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                self._fh = open(self.path, "ab")
                # Heal a torn tail (util helper shared with the perf
                # ledger): a SIGKILL mid-write can leave the file
                # without its final newline — appending straight on
                # would glue the new record onto the torn line and
                # corrupt BOTH.
                if util.file_needs_newline_heal(self.path):
                    self._fh.write(b"\n")
            self._fh.write(codec.encode(rec) + b"\n")
            self._fh.flush()
            self._apply_locked(rec)
        return seq

    def _apply_locked(self, rec: dict) -> None:
        # _apply mutates only dicts/ints; called under self._lock from
        # the append path (recovery runs before any thread exists).
        self._apply(rec)

    def admit(self, kind: str, fp: str, payload: dict) -> int:
        """Journal an admitted request BEFORE it is queued; returns the
        seq the settle must reference."""
        return self._append({"kind": kind, "fp": fp, **payload})

    def settle(self, seq: int, fp: str, result: dict) -> None:
        """Journal the answer for admit record ``seq`` — the durable
        reply a crashed client (or a restarted daemon's monitoring)
        reads back."""
        self._append({"kind": "settle", "of": int(seq), "fp": fp,
                      "verdict": result.get("valid?"),
                      "result": result})

    def stream_event(self, kind: str, sid: str, **fields) -> int:
        return self._append({"kind": kind, "sid": sid, **fields})

    # --- reading ------------------------------------------------------------

    def unsettled(self) -> list[dict]:
        """Admit records with no settle — what a restarted daemon must
        re-decide (in admission order)."""
        with self._lock:
            return [self._unsettled[k]
                    for k in sorted(self._unsettled)]

    def result_for(self, fp: str) -> tuple[str, dict | None]:
        """The journal-aware reconnect lookup (``result-fetch``):
        ``("settled", record)`` when a settle for this fingerprint
        exists; ``("pending", None)`` when it was admitted but not yet
        settled; ``("unknown", None)`` when never admitted (or the
        record was compacted away) — the settled record or an honest
        not-found, never a guess."""
        with self._lock:
            rec = self._results.get(fp)
            if rec is not None:
                return "settled", dict(rec)
            if any(r.get("fp") == fp
                   for r in self._unsettled.values()):
                return "pending", None
            return "unknown", None

    def stream_sessions(self, open_only: bool = True) -> dict[str, dict]:
        """Journaled stream sessions (``sid -> {model, appends,
        closed}``); by default only the ones never closed — the
        sessions a crash orphaned, re-adoptable via ``stream-open``
        with an explicit ``session``."""
        with self._lock:
            return {sid: {**s, "appends": [list(a) for a in s["appends"]]}
                    for sid, s in self._streams.items()
                    if not (open_only and s["closed"])}

    def depth(self) -> int:
        with self._lock:
            return len(self._unsettled)

    def stats(self) -> dict:
        with self._lock:
            return {"journal_path": self.path,
                    "journal_depth": len(self._unsettled),
                    "journal_settles": self._settled,
                    "journal_streams_open": sum(
                        1 for s in self._streams.values()
                        if not s["closed"]),
                    "journal_torn_lines": self._torn,
                    "journal_replays": self.replays}

    # --- maintenance --------------------------------------------------------

    def write_index(self) -> None:
        """Atomic monitoring snapshot beside the JSONL (derived state;
        replay trusts only the JSONL)."""
        try:
            util.write_json_atomic(self.path + ".index.json",
                                   {"version": JOURNAL_VERSION,
                                    "next_seq": self._next_seq,
                                    **self.stats()})
        except OSError:
            pass   # monitoring-grade: never take the daemon down

    def gc(self) -> dict:
        """Compact: rewrite keeping unsettled admits and OPEN stream
        sessions (their open+appends, so re-adoption still replays);
        settled pairs and closed sessions drop. Atomic (tmp+replace).
        Returns ``{"kept": n, "dropped": m}``."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
        records = self.load()
        with self._lock:
            keep: list[dict] = []
            dropped = 0
            open_sids = {sid for sid, s in self._streams.items()
                         if not s["closed"]}
            for rec in records:
                kind = rec.get("kind")
                if kind in ("check", "txn-check"):
                    take = rec.get("seq") in self._unsettled
                elif kind in ("stream-open", "stream-append"):
                    take = rec.get("sid") in open_sids
                else:   # settle / stream-close: pairs with dropped work
                    take = False
                if take:
                    keep.append(rec)
                else:
                    dropped += 1
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                for rec in keep:
                    fh.write(codec.encode(rec) + b"\n")
            os.replace(tmp, self.path)
            self._settled = 0
            self._streams = {sid: s for sid, s in self._streams.items()
                             if sid in open_sids}
        self.write_index()
        return {"kept": len(keep), "dropped": dropped}

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def freeze(self) -> None:
        """SIGKILL semantics (``CheckerService.crash()``): close AND
        refuse all further writes — ``close()`` alone would lazily
        reopen on the next append."""
        with self._lock:
            self._frozen = True
        self.close()


def describe(records: Iterable[dict]) -> list[dict[str, Any]]:
    """Compact per-record summaries for ``cli.py journal list``."""
    records = list(records)
    settled = {r.get("of", r.get("seq")) for r in records
               if r.get("kind") == "settle"}
    out = []
    for r in records:
        kind = r.get("kind")
        if kind in ("check", "txn-check"):
            out.append({"seq": r.get("seq"), "kind": kind,
                        "fp": str(r.get("fp", ""))[:16],
                        "model": r.get("model",
                                       r.get("consistency", "")),
                        "ops": len(r.get("history") or []),
                        "settled": r.get("seq") in settled})
        elif kind == "stream-open":
            out.append({"seq": r.get("seq"), "kind": kind,
                        "sid": r.get("sid"), "model": r.get("model")})
        elif kind == "stream-close":
            out.append({"seq": r.get("seq"), "kind": kind,
                        "sid": r.get("sid"), "how": r.get("how")})
    return out
