"""Checker-side chaos nemesis: the daemon tested like a database.

Everything in this repo rests on one invariant: the checker never
reports a verdict it did not compute. The daemon is now itself a
long-lived networked system (queues, worker threads, a journal), so it
gets the Jepsen treatment the databases under test get — a nemesis
injecting the daemon's own failure modes while concurrent clients
submit seeded histories, followed by a soundness audit:

    every answer (wire reply AND journal settle record) either equals
    the CPU oracle's verdict or is an honest ``valid? "unknown"`` —
    verdicts never FLIP, requests never VANISH (every journaled admit
    settles), and no request is answered twice on the wire (the
    ``done`` guard; one reply per request by protocol).

Injected event kinds (all deterministic test hooks, doc/env.md):

- ``wedge-check`` / ``wedge-batch`` — ``supervise.inject_wedge`` at
  the service sites (the ``JEPSEN_TPU_WEDGE`` machinery): the next
  dispatch blocks past its (injection-scoped) deadline.
- ``fault-check`` / ``fault-batch`` — ``supervise.inject_fault``
  (``JEPSEN_TPU_FAULT``): the next dispatch raises like a dead worker.
- ``worker-kill`` — ``CheckerService.inject_worker_kill``
  (``JEPSEN_TPU_SERVICE_KILL``): a worker THREAD dies with its batch
  in hand; the supervisor must requeue-once and respawn.
- ``device-loss`` — ``CheckerService.inject_device_loss``
  (``JEPSEN_TPU_SERVICE_DEVLOSS``): a worker's DEVICE dies (chip
  gone): its bin homes re-place onto surviving devices, the respawn
  rebinds off the lost device, and no verdict is lost or flipped.

:func:`run_chaos` drives an in-process daemon (real engines, real
sockets) through a seeded schedule — the chaos-gate tests run it at
1-worker and 4-worker pools. :func:`main` (``make fleet-smoke``) adds
the one failure mode an in-process harness cannot fake honestly: a
real ``SIGKILL`` of a daemon subprocess mid-flight (including an open
stream session), a restart on the same journal, and the
replay-and-re-decide audit. Chip-free: both legs force the CPU mesh.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

EVENT_KINDS = ("wedge-check", "wedge-batch", "fault-check",
               "fault-batch", "worker-kill", "device-loss")


def seeded_jobs(n: int, seed: int) -> list[tuple[str, list]]:
    """``n`` mixed histories: mostly one cas-register shape bin (so
    bins actually batch), some corrupted (definite invalid — verdict
    flips would be visible), a mutex minority (second kernel bin)."""
    from jepsen_tpu.lin import synth

    rng = random.Random(seed)
    jobs: list[tuple[str, list]] = []
    for i in range(n):
        r = rng.random()
        if r < 0.15:
            jobs.append(("mutex", list(synth.generate_mutex_history(
                20, concurrency=3, seed=seed * 1000 + i))))
        elif r < 0.45:
            jobs.append(("cas-register", list(synth.corrupt_history(
                synth.generate_register_history(
                    24, concurrency=3, seed=seed * 1000 + i,
                    value_range=3), seed=i))))
        else:
            jobs.append(("cas-register",
                         list(synth.generate_register_history(
                             24, concurrency=3, seed=seed * 1000 + i,
                             value_range=3, crash_prob=0.02,
                             max_crashes=2))))
    return jobs


def oracle_verdicts(jobs) -> list:
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import cpu, prepare

    factories = {"cas-register": m.cas_register, "mutex": m.mutex,
                 "register": m.register}
    return [cpu.check_packed(prepare.prepare(factories[name](),
                                             list(h)))["valid?"]
            for name, h in jobs]


def audit_journal(journal_path: str, oracle_by_fp: dict | None = None):
    """Audit a (finished) journal for the soundness invariant:
    returns ``(flips, unsettled, settles)`` — flips are settle records
    whose definite verdict contradicts the oracle; unsettled are admit
    records that VANISHED (no settle). ``oracle_by_fp`` maps history
    fingerprints to expected verdicts (omit to audit settlement
    only)."""
    from jepsen_tpu.service import journal as journal_mod

    j = journal_mod.Journal(journal_path)
    flips: list[dict] = []
    settles = [r for r in j.load() if r.get("kind") == "settle"]
    if oracle_by_fp:
        for rec in settles:
            want = oracle_by_fp.get(rec.get("fp"))
            got = rec.get("verdict")
            if want is not None and got in (True, False) \
                    and got != want:
                flips.append({"fp": rec.get("fp"), "want": want,
                              "got": got})
    return flips, j.unsettled(), settles


def run_chaos(*, histories: int = 60, events: int = 20,
              workers: int = 1, seed: int = 0,
              journal: str | None = None, clients: int = 4,
              svc_kw: dict | None = None,
              event_kinds=EVENT_KINDS) -> dict:
    """One seeded chaos run against an in-process daemon; returns the
    audit report (``report["sound"]`` is the gate).

    The quarantine ledger is redirected to a throwaway path for the
    run: injected faults are FAKE evidence and must never pollute the
    repo's real fault lore (``.jax_cache/quarantine.json``)."""
    from jepsen_tpu.lin import pack_dev, supervise
    from jepsen_tpu import models as m
    from jepsen_tpu.service.daemon import CheckerService
    from jepsen_tpu.service.protocol import CheckerClient

    jobs = seeded_jobs(histories, seed)
    want = oracle_verdicts(jobs)
    factories = {"cas-register": m.cas_register, "mutex": m.mutex}
    oracle_by_fp = {}
    for (name, h), w in zip(jobs, want):
        # The wire fingerprint (pre-pack columns — must match the
        # daemon's admission bit for bit, doc/service.md).
        fp = pack_dev.prepack_fingerprint(
            pack_dev.prepack(factories[name](), list(h)))
        oracle_by_fp[fp] = w

    q_prev = os.environ.get("JEPSEN_TPU_QUARANTINE")
    q_tmp = (journal or os.path.join(".jax_cache", "chaos")) \
        + ".quarantine.json"
    os.environ["JEPSEN_TPU_QUARANTINE"] = q_tmp
    rng = random.Random(seed + 1)
    schedule = [rng.choice(list(event_kinds)) for _ in range(events)]
    injected: dict[str, int] = {}
    svc = CheckerService("127.0.0.1", 0, workers=workers,
                         journal=journal, flush_ms_=10,
                         **(svc_kw or {})).start()
    results: dict[int, dict] = {}
    lock = threading.Lock()
    it = iter(list(enumerate(jobs)))
    done = threading.Event()

    def inject(kind: str) -> None:
        if kind == "wedge-check":
            supervise.inject_wedge("service-check", 1, deadline_s=0.2)
        elif kind == "wedge-batch":
            supervise.inject_wedge("service-batch", 1, deadline_s=0.2)
        elif kind == "fault-check":
            supervise.inject_fault("service-check", 1)
        elif kind == "fault-batch":
            supervise.inject_fault("service-batch", 1)
        elif kind == "worker-kill":
            svc.inject_worker_kill(1)
        elif kind == "device-loss":
            svc.inject_device_loss(1)
        injected[kind] = injected.get(kind, 0) + 1

    def nemesis() -> None:
        for kind in schedule:
            if done.wait(rng.uniform(0.02, 0.15)):
                # Clients finished early: fire the rest back-to-back
                # so the schedule's event COUNT is honored (they land
                # on the drain or are consumed by the next run).
                inject(kind)
                continue
            inject(kind)

    def client_loop() -> None:
        c = CheckerClient("127.0.0.1", svc.port)
        while True:
            with lock:
                nxt = next(it, None)
            if nxt is None:
                break
            i, (name, h) = nxt
            r = c.submit(name, h, req_id=i)
            with lock:
                results[i] = r
        c.close()

    try:
        nem = threading.Thread(target=nemesis, daemon=True)
        nem.start()
        threads = [threading.Thread(target=client_loop)
                   for _ in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        done.set()
        nem.join(10)
        stats_client = CheckerClient("127.0.0.1", svc.port)
        stats = stats_client.stats()
        stats_client.close()
    finally:
        done.set()
        svc.stop()
        # Leftover armed injections must not leak into the next run.
        supervise.reset_injections()
        if q_prev is None:
            os.environ.pop("JEPSEN_TPU_QUARANTINE", None)
        else:
            os.environ["JEPSEN_TPU_QUARANTINE"] = q_prev

    flips = []
    verdicts = {"match": 0, "unknown": 0, "missing": 0}
    for i, w in enumerate(want):
        got = results.get(i, {}).get("valid?")
        if got == w:
            verdicts["match"] += 1
        elif got == "unknown":
            verdicts["unknown"] += 1
        elif got is None:
            verdicts["missing"] += 1
        else:
            flips.append({"i": i, "want": w, "got": got})
    j_flips, j_unsettled, j_settles = ([], [], [])
    if journal:
        j_flips, j_unsettled, j_settles = audit_journal(journal,
                                                        oracle_by_fp)
    report = {
        "n": len(jobs), "workers": workers, "seed": seed,
        "verdicts": verdicts, "flips": flips,
        "journal_flips": j_flips,
        "journal_unsettled": len(j_unsettled),
        "journal_settles": len(j_settles),
        "injected": injected,
        "stats": {k: stats.get(k) for k in
                  ("decided", "requeues", "honest_fails",
                   "wedged_requests", "worker_deaths", "worker_kills",
                   "worker_wedges", "worker_respawns",
                   "device_losses", "placement_spills",
                   "watchdog_trips", "faults", "journal_replays",
                   "journal_depth", "dropped_responses")},
        # Soundness: no flipped verdict anywhere, every request
        # answered, every journaled admit settled.
        "sound": (not flips and not j_flips
                  and verdicts["missing"] == 0
                  and (not journal or not j_unsettled)),
    }
    return report


# --- the fleet smoke (`make fleet-smoke`) ----------------------------------


def _force_cpu_env(extra: dict | None = None) -> dict:
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra or {})
    return env


def _spawn_daemon(env: dict) -> tuple[subprocess.Popen, int]:
    proc = subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.cli", "serve-checker",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env)
    line = proc.stdout.readline()
    # "checker daemon on 127.0.0.1:PORT (queue bound ...)"
    try:
        port = int(line.split(":")[1].split()[0].strip("()"))
    except (IndexError, ValueError):
        proc.kill()
        raise RuntimeError(f"daemon did not announce a port: {line!r}")
    return proc, port


def main() -> int:
    t_start = time.time()
    # CPU mesh BEFORE any jax backend init (CLAUDE.md: the TPU plugin
    # force-selects its platform; the smoke must never take the chip).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from jepsen_tpu import models as m
    from jepsen_tpu import util
    from jepsen_tpu.lin import cpu, prepare, synth
    from jepsen_tpu.service.protocol import CheckerClient

    util.enable_compile_cache()
    base = os.path.join(".jax_cache", "fleet_smoke")
    os.makedirs(base, exist_ok=True)
    for f in os.listdir(base):
        try:
            os.remove(os.path.join(base, f))
        except OSError:
            pass
    out: dict = {"checks": []}
    ok = True

    # --- leg 1: in-process chaos (wedge+fault+worker-kill) ------------------
    report = run_chaos(histories=24, events=10, workers=2, seed=7,
                       journal=os.path.join(base, "chaos.jsonl"))
    out["checks"].append({"leg": "chaos", "sound": report["sound"],
                          "verdicts": report["verdicts"],
                          "injected": report["injected"],
                          "stats": report["stats"]})
    ok = ok and report["sound"]

    # --- leg 1b: device loss under load (the placement re-home) -------------
    # Every event is a device loss: the 2-worker pool must keep
    # answering (survivor re-placement, zero lost/flipped verdicts)
    # and the losses must be visible in the obs event feed and the
    # worker counters — the chip-loss acceptance shape.
    from jepsen_tpu.obs import metrics as obs_metrics

    report = run_chaos(histories=16, events=3, workers=2, seed=11,
                       journal=os.path.join(base, "devloss.jsonl"),
                       event_kinds=("device-loss",))
    snap = obs_metrics.REGISTRY.snapshot()
    feed_kinds = [e.get("kind") for e in snap.get("events", [])]
    losses = report["stats"].get("device_losses") or 0
    rec = {"leg": "device-loss", "sound": report["sound"],
           "verdicts": report["verdicts"],
           "device_losses": losses,
           "worker_respawns": report["stats"].get("worker_respawns"),
           "event_counter":
               snap.get("counters", {}).get("event_device-loss", 0),
           "in_event_feed": "device-loss" in feed_kinds,
           "ok": (report["sound"] and losses >= 1
                  and "device-loss" in feed_kinds
                  and snap.get("counters", {}).get(
                      "event_device-loss", 0) >= losses)}
    out["checks"].append(rec)
    ok = ok and rec["ok"]

    # --- leg 2: SIGKILL mid-flight -> restart -> replay -> parity -----------
    journal = os.path.join(base, "restart.jsonl")
    stream_ckpt = os.path.join(base, "stream.ckpt")
    child_env = _force_cpu_env({
        "JEPSEN_TPU_SERVICE_JOURNAL": journal,
        "JEPSEN_TPU_SERVICE_WORKERS": "2",
        "JEPSEN_TPU_STREAM_CKPT": stream_ckpt,
        "JEPSEN_TPU_SERVICE_STATS": os.path.join(base, "stats.json"),
        "JEPSEN_TPU_QUARANTINE": os.path.join(base, "quarantine.json"),
        # Two bin batches wedge IN FLIGHT (long injected deadline,
        # blocking both workers): the SIGKILL lands while their
        # requests — and everything queued behind them — are
        # admitted-but-undecided, so the journal is guaranteed an
        # unsettled tail to replay.
        "JEPSEN_TPU_WEDGE": "service-batch:2:120,service-check:2:120",
    })
    proc, port = _spawn_daemon(child_env)
    h = list(synth.generate_register_history(
        120, concurrency=4, seed=21, value_range=5, crash_prob=0.02,
        max_crashes=2))
    want_stream = cpu.check_packed(
        prepare.prepare(m.cas_register(), list(h)))["valid?"]
    jobs = seeded_jobs(8, seed=31)
    want = oracle_verdicts(jobs)
    from jepsen_tpu.lin import pack_dev
    fps = [pack_dev.prepack_fingerprint(
        pack_dev.prepack({"cas-register": m.cas_register,
                          "mutex": m.mutex}[name](), list(hh)))
        for name, hh in jobs]
    oracle_by_fp = dict(zip(fps, want))

    # One stream session, half-fed FIRST (while the workers are still
    # free): its frontier must survive the kill via the per-sid
    # checkpoint + journaled appends.
    sc = CheckerClient("127.0.0.1", port, timeout=60)
    sid = sc.stream_open("cas-register")
    half = len(h) // 2
    step = max(1, half // 3)
    appends_before = 0
    for i in range(0, half, step):
        st = sc.stream_append(sid, h[i:i + step])
        if st.get("type") == "stream-state":
            appends_before += 1

    # Then the check burst: the armed service-batch wedges block both
    # workers, so these sit admitted-but-undecided for the SIGKILL.
    def submit(i):
        c = CheckerClient("127.0.0.1", port, timeout=300)
        c.submit(jobs[i][0], jobs[i][1], req_id=i)
        c.close()

    threads = [threading.Thread(target=submit, args=(i,), daemon=True)
               for i in range(len(jobs))]
    for t in threads:
        t.start()
    # Wait until the journal shows unsettled admits (the two wedged
    # submits), then SIGKILL mid-flight.
    from jepsen_tpu.service import journal as journal_mod
    deadline = time.time() + 120
    while time.time() < deadline:
        if os.path.exists(journal) \
                and journal_mod.Journal(journal).depth() >= 2:
            break
        time.sleep(0.2)
    depth_at_kill = journal_mod.Journal(journal).depth()
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(30)
    out["checks"].append({"leg": "sigkill",
                          "unsettled_at_kill": depth_at_kill,
                          "ok": depth_at_kill >= 2})
    ok = ok and depth_at_kill >= 2

    # Restart on the same journal, no injections: replay re-decides.
    child_env2 = {k: v for k, v in child_env.items()
                  if k != "JEPSEN_TPU_WEDGE"}
    proc2, port2 = _spawn_daemon(child_env2)
    try:
        c2 = CheckerClient("127.0.0.1", port2, timeout=300)
        deadline = time.time() + 300
        depth = None
        while time.time() < deadline:
            st = c2.stats()
            depth = st.get("journal_depth")
            if depth == 0:
                break
            time.sleep(0.3)
        flips, unsettled, settles = audit_journal(journal,
                                                  oracle_by_fp)
        rec = {"leg": "replay", "journal_depth": depth,
               "journal_replays": st.get("journal_replays"),
               "settles": len(settles), "flips": flips,
               "unsettled": len(unsettled),
               "ok": (depth == 0 and not flips and not unsettled
                      and st.get("journal_replays", 0) >= 2)}
        out["checks"].append(rec)
        ok = ok and rec["ok"]

        # Re-adopt the stream session; feed the rest; parity.
        opened = c2.stream_open("cas-register", session=sid)
        for i in range(half, len(h), step):
            c2.stream_append(sid, h[i:i + step])
        rw = c2.stream_finalize(sid)
        rec = {"leg": "stream-resume", "want": want_stream,
               "got": rw.get("valid?"),
               "replayed_appends": opened.get("replayed_appends"),
               "resumed_from_row":
                   (rw.get("stream") or {}).get("resumed_from_row"),
               "ok": (rw.get("valid?") == want_stream
                      and opened.get("replayed_appends", 0)
                      >= appends_before)}
        out["checks"].append(rec)
        ok = ok and rec["ok"]
        c2.shutdown()
        c2.close()
    finally:
        try:
            proc2.wait(30)
        except subprocess.TimeoutExpired:
            proc2.kill()
    out["ok"] = ok
    # Cross-run perf ledger (doc/observability.md § Perf ledger):
    # record() never raises — a ledger failure cannot cost the smoke.
    from jepsen_tpu.obs import ledger as perf_ledger

    perf_ledger.record("fleet-smoke", kind="smoke",
                       wall_s=time.time() - t_start, verdict=ok)
    print(json.dumps(out, default=str))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
