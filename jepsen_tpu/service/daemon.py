"""The checker daemon: shape-binned continuous batching on a warm chip.

Pipeline (each stage its own thread(s), queues between them):

1. **Admission** — one handler thread per client connection reads
   framed requests (protocol.py), PREPACKS the history
   (``pack_dev.prepack``: pairing, interning, window scan — the cheap
   host half; the O(n·W) grid paint is deferred to the worker, where a
   flushed bin's grids materialize as ONE batched device program on
   the worker's placed device, doc/service.md § Device packing),
   fingerprints it over the pre-pack columns, computes its shape-bin
   key, and admits it under the IN-FLIGHT BOUND (admitted and not yet
   answered; bounding only the queue would leak, since the scheduler
   drains it into necessarily-unbounded shape bins). Past the bound a
   request is answered ``overload`` immediately — backpressure, never
   a silent drop, never an unbounded buffer that hides the capacity
   problem. A client that disconnects mid-request costs nothing: its
   in-flight verdicts are discarded on the dead connection
   (``dropped_responses``) and the daemon keeps serving.
2. **Scheduler** — drains admissions into per-shape bins and flushes a
   bin to the worker when it reaches ``max_batch`` OR its oldest
   request has waited ``flush_ms`` (continuous batching: a full bin
   never waits, a lone request waits at most the flush window).
3. **Worker** — one thread owning the device. A flushed bin of
   same-shape histories decides as ONE vmapped
   :func:`jepsen_tpu.lin.batched.try_check_batch` program (duplicate
   fingerprints decide once and fan out; the key axis is optionally
   padded to a power of two so each (shape, K-bucket) program compiles
   exactly once — zero retrace after warmup). Keys the batch declines
   (:class:`jepsen_tpu.lin.batched.Decline` names the axis) fall
   through to per-request ``lin.device_check_packed`` under the PR 5
   supervision ladder with a per-request deadline: a WEDGE becomes an
   honest ``overflow: wedge`` unknown, a FAULT requeues the in-flight
   requests ONCE (as singles, off the suspect batch program) and then
   fails honestly — the daemon itself never dies with the worker.

The quarantine ledger records faulting service shapes under the
``service-batch`` / ``service-check`` sites (observability, like the
base engine rungs — the in-daemon routing is the requeue policy, and
the engine-internal sites keep their own ledger routing).

**Fleet grade (doc/service.md § Fleet).** Three hardening axes over
the same pipeline:

- **Durable request journal** (journal.py,
  ``JEPSEN_TPU_SERVICE_JOURNAL``): every admitted check / txn-check /
  stream event appends to a JSONL journal before it is queued; the
  answer appends a settle record. A restarted daemon replays the
  unsettled entries and re-decides them automatically
  (``journal_replays``); a crashed stream session's carried frontier
  survives via its per-sid ``JEPSEN_TPU_STREAM_CKPT`` checkpoint and
  is re-adoptable (``stream-open`` with an explicit ``session``).
- **Crash-recovering worker pool** (``JEPSEN_TPU_SERVICE_WORKERS``,
  default 1 — the single-chip driver shape is unchanged): N decide
  workers behind the one admission+binning tier. The scheduler's
  supervisor tick detects a dead or deadline-wedged worker, requeues
  its in-hand bin ONCE (the fault-requeue promise promoted from
  per-batch to per-worker), ledger-records the bin shape, and
  respawns — the daemon never dies with a worker, and the ``done``
  guard means it never answers a verdict twice.
- **Chaos hooks** (chaos.py drives them): ``inject_worker_kill()`` /
  ``JEPSEN_TPU_SERVICE_KILL`` make a worker thread die with its batch
  in hand; ``supervise.inject_fault`` / ``JEPSEN_TPU_FAULT`` fault a
  supervised dispatch; ``crash()`` is the in-process SIGKILL
  approximation (no drain, no settles) for restart-recovery tests.

Every knob is tabled in doc/env.md (`JEPSEN_TPU_SERVICE_*`); stats are
served on the wire (``stats`` message / ``cli.py service-stats``) and
snapshotted to ``JEPSEN_TPU_SERVICE_STATS`` for ``web.py``'s
``/service`` page.
"""

from __future__ import annotations

import hashlib
import os
import queue
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from jepsen_tpu import codec, util
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import trace as obs_trace
from jepsen_tpu.service import journal as journal_mod
from jepsen_tpu.service import placement as placement_mod
from jepsen_tpu.service import protocol
from jepsen_tpu.suites.common import SocketIO

_REQUEUE_MAX = 1       # fault requeues per request, then honest fail
_LATENCY_RING = 1024   # recent end-to-end latencies kept for p50/p99
_STATS_WRITE_EVERY_S = 10.0


def default_port() -> int:
    return util.env_int("JEPSEN_TPU_SERVICE_PORT", protocol.DEFAULT_PORT)


def queue_bound() -> int:
    return util.env_int("JEPSEN_TPU_SERVICE_QUEUE", 1024)


def flush_ms() -> float:
    return util.env_float("JEPSEN_TPU_SERVICE_FLUSH_MS", 50.0)


def max_batch() -> int:
    return util.env_int("JEPSEN_TPU_SERVICE_MAX_BATCH", 256)


def request_deadline_s() -> float:
    return util.env_float("JEPSEN_TPU_SERVICE_DEADLINE_S", 600.0)


def pad_pow2() -> bool:
    return os.environ.get("JEPSEN_TPU_SERVICE_PAD_POW2", "1") != "0"


def stats_path() -> str:
    return os.environ.get("JEPSEN_TPU_SERVICE_STATS", "") or os.path.join(
        util.cache_dir(), "service_stats.json")


def stream_session_bound() -> int:
    """Concurrent stream-check sessions the daemon holds open: each
    pins a carried frontier + packer state in memory between appends,
    so the bound is a memory/abuse guard like the in-flight bound."""
    return util.env_int("JEPSEN_TPU_STREAM_SESSIONS", 4)


def stream_bins_enabled() -> bool:
    """svc-stream bins (``JEPSEN_TPU_SERVICE_STREAM_BINS``, default
    on): daemon stream sessions DEFER their increments into per-model
    bins, and concurrent sessions sharing a traced shape decide as ONE
    vmapped carried-frontier program
    (:func:`jepsen_tpu.lin.batched.try_stream_batch`) — solo fallback
    on decline, exactly like check bins. ``0`` restores the
    per-session solo dispatch path."""
    return os.environ.get("JEPSEN_TPU_SERVICE_STREAM_BINS", "1") != "0"


def worker_count() -> int:
    """Decide workers (``JEPSEN_TPU_SERVICE_WORKERS``). Default 1 —
    one thread owning the one device, the single-chip driver shape.
    CPU-mesh tests and multi-chip hosts raise it; with N > 1 each
    worker binds to ``jax.devices()[slot % N_dev]`` and the placement
    policy (placement.py) routes flushed bins by bin -> device
    affinity with least-loaded spill."""
    return util.env_int("JEPSEN_TPU_SERVICE_WORKERS", 1)


def worker_deadline_s(deadline_s: float) -> float:
    """How long a worker may make NO PROGRESS (no request started or
    answered — the progress clock refreshes per single and per finish,
    not per work item, since a decline-heavy bin legitimately runs
    many sequential supervised dispatches) before the supervisor
    declares it wedged, requeues its pending bin once, and respawns a
    replacement. Default derives from the per-request deadline: the
    in-batch supervision (``supervise.call``) already bounds every
    dispatch, so the worker-level deadline is a backstop strictly
    wider than it — it fires only for non-dispatch hangs."""
    env = util.env_float("JEPSEN_TPU_SERVICE_WORKER_DEADLINE_S", 0.0)
    return env if env > 0 else deadline_s * 2 + 60.0


@dataclass(eq=False)
class Request:
    """One queued check: wire identity + packed shape + reply route.
    ``eq=False``: requests are identities, never compared by value
    (packed carries numpy arrays)."""

    rid: Any
    model_name: str
    model: Any
    history: list
    packed: Any                    # PackedHistory | None (unpackable)
    bin: str                       # shape-bin key (supervise codec)
    fingerprint: str               # history identity (supervise codec)
    respond: Callable[[dict], None]
    prepack: Any = None            # pack_dev.PrePacked | None: admitted
    #                                but not yet materialized — the
    #                                worker paints the grids (a batched
    #                                device program when the wave allows)
    t_enqueue: float = field(default_factory=time.monotonic)
    attempts: int = 0              # fault requeues consumed
    no_batch: bool = False         # post-fault: keep off the batch path
    done: bool = False             # answered (guards double-finish)
    kind: str = "check"            # "check" | "txn" (routing in
    #                                _check_single; txn never bins)
    txn_kw: dict | None = None     # txn-check params (kind == "txn")
    journal_seq: int | None = None  # journal admit seq (settle target)


@dataclass(eq=False)
class _WorkerState:
    """One decide worker: its thread, ITS OWN work queue (the
    placement policy routes flushed bins to slots, so each slot
    queues independently), its bound device, and the work item IN
    HAND — what the supervisor requeues if the thread dies or wedges
    mid-item. ``slot`` is the pool position: a respawned worker
    inherits its predecessor's slot, queue, and device, so bin homes
    survive worker deaths."""

    wid: int
    slot: int = 0
    thread: threading.Thread | None = None
    q: queue.Queue = field(default_factory=queue.Queue)
    device: Any = None             # jax device (None: thread default)
    device_ix: int | None = None
    device_lost: bool = False      # chaos: respawn rebinds elsewhere
    busy: Any = None               # batch / ("stream", job) in hand
    busy_since: float = 0.0
    items: int = 0                 # work items completed
    busy_s: float = 0.0            # seconds spent on items
    compiles: int = 0              # XLA compiles attributed (approx:
    #                                process-meter delta per item)
    abandoned: bool = False        # supervisor gave up on it; the
    #                                thread exits at its next loop top


@dataclass(eq=False)
class _StreamIncr:
    """One deferred stream increment riding the scheduler's bins: the
    scheduler only touches ``bin`` (it bins these exactly like
    Requests), the worker pool turns a flushed svc-stream bin into one
    vmapped :func:`jepsen_tpu.lin.batched.try_stream_batch` program,
    and the blocked connection handler wakes on ``done`` to send the
    fresh session status."""

    sess: "StreamSession"
    bin: str
    done: threading.Event = field(default_factory=threading.Event)
    reply: dict | None = None
    error: str | None = None
    t_enqueue: float = field(default_factory=time.monotonic)


@dataclass(eq=False)
class StreamSession:
    """One daemon-held streaming session (doc/streaming.md): the
    StreamChecker (carried frontier + incremental packer) plus its
    OWNING connection — a dropped client's sessions are reaped and
    their slots freed; another connection can never touch them.
    ``lock`` serializes this session's checker work across the worker
    POOL (StreamChecker is not thread-safe; with N>1 workers, or a
    deadline-expired job still running on an abandoned worker, two
    jobs for one session could otherwise interleave)."""

    sid: str
    model_name: str
    checker: Any
    sock: Any
    opened: float = field(default_factory=time.monotonic)
    appends: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock)


def bin_key(packed) -> str:
    """The traced-shape bin of a packed history: engine route x window
    bucket x state width x row bucket x kernel — reusing the
    supervision layer's shape-key codec so ledger entries, service
    stats, and triage all speak one shape language. Two histories in
    one bin batch into one vmapped program with (at most) one compile
    per occupancy bucket."""
    from jepsen_tpu.lin import dense, supervise

    kern = packed.kernel.name if packed.kernel is not None else "none"
    r_pad = 1 << max(4, (packed.R - 1).bit_length()) if packed.R else 16
    plan = dense.plan(packed)
    if plan is not None:
        w, ns, _, _ = plan
        return supervise.shape_key("svc-dense", cap=ns, window=w,
                                   kernel=kern, rows=r_pad)
    w_bucket = 1 << max(3, (packed.window - 1).bit_length())
    return supervise.shape_key("svc-sparse",
                               cap=int(packed.state_width),
                               window=w_bucket, kernel=kern, rows=r_pad)


def _txn_kw(msg: dict) -> dict:
    """The txn-check params carried by a wire frame / journal record
    (everything ``checker.txn_cycles`` takes)."""
    anomalies = msg.get("anomalies")
    return {"anomalies": tuple(anomalies) if anomalies else None,
            "consistency": msg.get("consistency", "serializable"),
            "realtime": msg.get("realtime"),
            "algorithm": msg.get("algorithm", "tpu")}


def stream_bin(model_name: str) -> str:
    """The svc-stream bin family: one key per model name. Coarser
    than the check bins on purpose — concurrent sessions of one model
    usually share the traced increment shape (same kernel family;
    try_stream_batch regroups by the EXACT (step, S, window) key and
    declines mixes), and the placement policy keeps the whole family
    on one device so a session's programs never migrate."""
    return f"svc-stream|{model_name}"


def _txn_bin(kw: dict) -> str:
    """Txn requests never bin (the daemon decides them per-request
    under the supervised fallthrough — ROADMAP's "txn-check on the
    same wire" rung); the key exists for stats/ledger attribution."""
    return f"svc-txn|{kw['algorithm']}|{kw['consistency']}"


class CheckerService:
    """The daemon. ``start()`` binds and spawns the pipeline;
    ``serve_forever()`` blocks; ``stop()`` drains and joins.

    ``check_fn`` / ``batch_fn`` are test hooks replacing the device
    paths (default ``lin.device_check_packed`` /
    ``lin.batched.try_check_batch``)."""

    def __init__(self, host: str = "127.0.0.1", port: int | None = None,
                 *, bound: int | None = None,
                 flush_ms_: float | None = None,
                 max_batch_: int | None = None,
                 deadline_s: float | None = None,
                 stats_file: str | None = None,
                 workers: int | None = None,
                 journal: str | None = None,
                 check_fn: Callable | None = None,
                 batch_fn: Callable | None = None,
                 stream_batch_fn: Callable | None = None):
        self.host = host
        self.port = port if port is not None else default_port()
        self.bound = bound if bound is not None else queue_bound()
        self.flush_s = (flush_ms_ if flush_ms_ is not None
                        else flush_ms()) / 1000.0
        self.max_batch = max_batch_ if max_batch_ is not None \
            else max_batch()
        self.deadline_s = deadline_s if deadline_s is not None \
            else request_deadline_s()
        self.stats_file = stats_file if stats_file is not None \
            else stats_path()
        self.n_workers = max(1, workers if workers is not None
                             else worker_count())
        self.worker_deadline = worker_deadline_s(self.deadline_s)
        self.journal_file = journal if journal is not None \
            else journal_mod.journal_path()
        self._check_fn = check_fn
        self._batch_fn = batch_fn
        self._stream_batch_fn = stream_batch_fn
        self.stream_bins = stream_bins_enabled()

        # The admission queue itself is unbounded; the BOUND is on
        # requests IN FLIGHT (admitted, not yet answered) — bounding
        # only the queue would leak, since the scheduler immediately
        # drains it into (necessarily unbounded) shape bins.
        self._queue: queue.Queue[Request] = queue.Queue()
        self._inflight = 0
        self._bins: dict[str, list[Request]] = {}
        self._bins_lock = threading.Lock()
        self._stop = threading.Event()
        self._stop_lock = threading.Lock()
        self._stopped = threading.Event()
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._workers: list[_WorkerState] = []
        self._abandoned: list[threading.Thread] = []
        self._worker_seq = 0
        self._kill_armed = util.env_int("JEPSEN_TPU_SERVICE_KILL", 0)
        self._devloss_armed = util.env_int(
            "JEPSEN_TPU_SERVICE_DEVLOSS", 0)
        self._kill_lock = threading.Lock()
        self._placement = placement_mod.Placement(self.n_workers)
        self._devices: list = []       # jax devices (n_workers > 1)
        self._lost_devices: set[int] = set()
        self._crashed = False
        self._journal: journal_mod.Journal | None = None

        self._streams: dict[str, StreamSession] = {}
        self._streams_lock = threading.Lock()
        self._stream_seq = 0
        self.stream_bound = stream_session_bound()

        self._stats_lock = threading.Lock()
        self._stats: dict = {"decline_axes": {}, "bin_decide_s": {},
                             "bin_requests": {}}
        self._latencies: list[float] = []   # ring, _LATENCY_RING cap
        self._last_stats_write = 0.0

    # --- observability ------------------------------------------------------

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            util.stat_bump(self._stats, key, n)

    def _note_latency(self, seconds: float) -> None:
        with self._stats_lock:
            self._latencies.append(seconds)
            if len(self._latencies) > _LATENCY_RING:
                del self._latencies[:len(self._latencies)
                                    - _LATENCY_RING]

    @staticmethod
    def _percentile(xs: list[float], q: float) -> float | None:
        if not xs:
            return None
        s = sorted(xs)
        return s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]

    def stats(self) -> dict:
        """Snapshot: counters + queue/bin depths + latency percentiles
        + the process-wide XLA compile meter."""
        with self._stats_lock:
            # dict(self._stats) first (one C-level copy): the
            # supervision layer inserts keys into this dict WITHOUT
            # our lock (supervise._note_event is deliberately
            # lock-free), and a Python-level comprehension over the
            # live dict could see it resize mid-iteration.
            items = dict(self._stats)
            out = util.round_stats(
                {k: (dict(v) if isinstance(v, dict) else v)
                 for k, v in items.items()}, 3)
            lats = list(self._latencies)
        with self._bins_lock:
            out["bin_depths"] = {k: len(v)
                                 for k, v in self._bins.items() if v}
        out["queue_depth"] = self._queue.qsize()
        out["queue_bound"] = self.bound
        with self._streams_lock:
            out["stream_sessions_open"] = len(self._streams)
            out["stream_session_bound"] = self.stream_bound
            if self._streams:
                out["stream_sessions"] = {
                    s.sid: {"model": s.model_name, "appends": s.appends,
                            **s.checker.status()}
                    for s in self._streams.values()}
        with self._stats_lock:
            out["in_flight"] = self._inflight
        out["workers"] = len(self._workers) or self.n_workers
        out["workers_busy"] = sum(1 for w in self._workers
                                  if w.busy is not None)
        out["placement"] = self._placement_block()
        if self._journal is not None:
            out.update(self._journal.stats())
        batches = out.get("batches", 0)
        out["avg_occupancy"] = round(
            out.get("batched_requests", 0) / batches, 2) if batches \
            else None
        out["latency_p50_s"] = self._percentile(lats, 0.50)
        out["latency_p99_s"] = self._percentile(lats, 0.99)
        out["latency_samples"] = len(lats)
        out.update(_compile_meter_snapshot())
        out.update(_pack_meter_snapshot())
        return protocol.jsonable(out)

    def _placement_block(self) -> dict:
        """Per-device fleet telemetry: the placement policy's counters
        plus each worker slot's device, queue depth, busy-seconds,
        item and compile counts — the ISSUE's 'per-device queue depth
        / busy-seconds / compile counts' surface for service-stats
        and the /service page."""
        block = self._placement.snapshot()
        block["workers"] = [
            {"wid": w.wid, "slot": w.slot,
             "device": (str(self._devices[w.device_ix])
                        if w.device_ix is not None and self._devices
                        else None),
             "queue_depth": w.q.qsize(),
             "busy": w.busy is not None,
             "items": w.items,
             "busy_s": round(w.busy_s, 3),
             "compiles": w.compiles}
            for w in self._workers]
        if self._lost_devices:
            block["lost_devices"] = sorted(self._lost_devices)
        return block

    def _write_stats_snapshot(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_stats_write \
                < _STATS_WRITE_EVERY_S:
            return
        self._last_stats_write = now
        path = self.stats_file
        if not path:
            return
        try:
            snap = dict(self.stats())
            snap["written_at"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            snap["addr"] = f"{self.host}:{self.port}"
            util.write_json_atomic(path, snap)
        except Exception:  # noqa: BLE001 - monitoring-grade: a stats
            pass   # write must never take the scheduler thread down

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> "CheckerService":
        from jepsen_tpu.util import enable_compile_cache

        enable_compile_cache()   # the warm worker's whole point
        _install_compile_meter()
        # The service stats dict as a live named view of the obs
        # registry (doc/observability.md): one snapshot codec across
        # host-stats / mesh-stats / service stats.
        obs_metrics.REGISTRY.view("service", self._stats)
        if self.journal_file:
            self._journal = journal_mod.Journal(self.journal_file)
        self._listener = socket.create_server(
            (self.host, self.port), reuse_port=False)
        # Closing a socket does NOT wake a thread blocked in accept()
        # on Linux; poll with a timeout so stop() takes ~0.5 s, not a
        # join timeout.
        self._listener.settimeout(0.5)
        self.port = self._listener.getsockname()[1]
        # Device binding only exists at n_workers > 1: the workers=1
        # driver shape must never import jax here (bit-identical to
        # the pre-placement daemon — the device is whatever the one
        # worker thread's jax default already is).
        if self.n_workers > 1:
            try:
                import jax

                self._devices = list(jax.devices())
            except Exception:  # noqa: BLE001 - no backend: unbound
                self._devices = []
        # Workers FIRST: the scheduler's supervisor tick dereferences
        # the pool on its first iteration.
        self._workers = [self._spawn_worker(slot)
                         for slot in range(self.n_workers)]
        for name, fn in (("accept", self._accept_loop),
                         ("scheduler", self._scheduler_loop)):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"svc-{name}")
            t.start()
            self._threads.append(t)
        # Journal replay LAST: re-decides ride the live pipeline.
        self._replay_journal()
        return self

    def _spawn_worker(self, slot: int,
                      inherit: _WorkerState | None = None) \
            -> _WorkerState:
        """Spawn the worker for ``slot``. A respawn (``inherit``)
        keeps the predecessor's queue and device — pending work and
        bin homes survive a worker death; only device LOSS rebinds
        (to the least-loaded surviving device, after the placement
        map forgot this slot's homes)."""
        self._worker_seq += 1
        st = _WorkerState(wid=self._worker_seq, slot=slot)
        if inherit is not None:
            st.q = inherit.q
            if inherit.device_lost:
                st.device_ix = self._rebind_device()
            else:
                st.device_ix = inherit.device_ix
        elif self._devices:
            st.device_ix = slot % len(self._devices)
        if st.device_ix is not None and self._devices:
            st.device = self._devices[st.device_ix]
        st.thread = threading.Thread(
            target=self._worker_loop, args=(st,), daemon=True,
            name=f"svc-worker-{st.wid}")
        st.thread.start()
        return st

    def _rebind_device(self) -> int | None:
        """Least-loaded surviving device (by bound worker count) for
        a respawn after device loss."""
        if not self._devices:
            return None
        alive = [i for i in range(len(self._devices))
                 if i not in self._lost_devices] \
            or list(range(len(self._devices)))
        loads = {i: 0 for i in alive}
        for w in self._workers:
            if w.device_ix in loads:
                loads[w.device_ix] += 1
        return min(alive, key=lambda i: (loads[i], i))

    def serve_forever(self) -> None:
        while not self._stop.wait(0.5):
            pass

    def stop(self, timeout: float = 30.0) -> None:
        """Drain-and-stop: admissions close, queued bins flush and
        decide, stats snapshot written. Idempotent AND blocking: a
        second caller waits for the first stop to finish (the shutdown
        wire message races the client's own svc.stop())."""
        with self._stop_lock:
            first = not self._stop.is_set()
            self._stop.set()
        if not first:
            self._stopped.wait(timeout)
            return
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout)
        # The scheduler flushed every bin before exiting; the
        # sentinels queue BEHIND them on each slot queue, so the
        # workers drain all pending work. Abandoned-but-alive threads
        # never consume from a queue again (they exit on the
        # ``abandoned`` flag at their loop top), so one sentinel per
        # slot suffices.
        for w in self._workers:
            w.q.put(None)
        for w in self._workers:
            if w.thread is not None:
                w.thread.join(timeout)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._write_stats_snapshot(force=True)
        if self._journal is not None:
            self._journal.write_index()
            self._journal.close()
        self._stopped.set()

    def crash(self) -> None:
        """Chaos/test hook: die like SIGKILL (the in-process
        approximation restart-recovery tests use). No drain, no
        further journal settles or wire replies — the journal is left
        exactly as a process kill leaves it (admits without settles),
        the listener and every connection drop, and in-flight worker
        results are suppressed. The object is dead afterwards; start
        a NEW CheckerService on the same journal to model the
        restart."""
        self._crashed = True
        with self._stop_lock:
            self._stop.set()
        if self._journal is not None:
            self._journal.freeze()   # close() alone would lazily
            #                          reopen on an in-flight settle
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        # Unblock worker threads so test processes don't accumulate
        # them (each drops its work at the crashed check in its loop).
        for w in self._workers:
            w.q.put(None)
        self._stopped.set()

    # --- journal replay -----------------------------------------------------

    def _replay_journal(self) -> None:
        """Re-decide every unsettled journal entry (restart recovery):
        each replays through the live pipeline as a normal request
        whose reply route is the journal settle record alone (the
        original connection died with the previous process — its
        client already completed indeterminate, per the wire
        contract)."""
        if self._journal is None:
            return
        replayed = 0
        for rec in self._journal.unsettled():
            seq, fp = rec.get("seq"), rec.get("fp", "")
            try:
                req = self._request_from_journal(rec)
            except Exception as e:  # noqa: BLE001 - a corrupt record
                # settles honestly instead of wedging the replay
                self._journal.settle(seq, fp, {
                    "valid?": "unknown",
                    "error": f"journal replay failed: {e!r}"})
                self._bump("journal_replay_errors")
                continue
            req.journal_seq = seq
            with self._stats_lock:
                self._inflight += 1   # replays bypass the admission
                #                       bound: they WERE admitted once
            self._queue.put(req)
            replayed += 1
        if replayed:
            self._journal.replays += replayed
            self._bump("journal_replays", replayed)
            obs_metrics.REGISTRY.event("journal-replay", n=replayed)

    def _request_from_journal(self, rec: dict) -> Request:
        history = protocol.history_from_wire(rec.get("history") or [])
        if rec.get("kind") == "txn-check":
            kw = _txn_kw(rec)
            return Request(rid=f"journal-{rec.get('seq')}",
                           model_name="txn", model=None,
                           history=history, packed=None,
                           bin=_txn_bin(kw), fingerprint=rec.get("fp"),
                           respond=lambda msg: None, kind="txn",
                           txn_kw=kw, no_batch=True)
        model = protocol.model_by_name(rec.get("model"))
        pre, key, fp = self._pack_admission(model, history)
        if fp is None:
            fp = rec.get("fp")
        return Request(rid=f"journal-{rec.get('seq')}",
                       model_name=rec.get("model"), model=model,
                       history=history, packed=None, prepack=pre,
                       bin=key, fingerprint=fp,
                       respond=lambda msg: None)

    # --- admission ----------------------------------------------------------

    def _pack_admission(self, model, history):
        """The shared admission pack (wire ``_admit`` + journal
        replay — ONE shape instead of two hand-rolled prepare blocks):
        prepack only (pairing, interning, window scan), binned and
        fingerprinted over the pre-pack columns
        (``pack_dev.prepack_fingerprint`` — the same function
        ``protocol.request_fingerprint`` computes client-side). The
        grids materialize later on the worker's placed device
        (doc/service.md § Device packing). Returns ``(pre, bin, fp)``;
        ``(None, "svc-cpu|<kind>", None)`` for an unpackable history —
        still a legitimate check (lin.analysis routes it to the
        unbounded host search), it just never bins."""
        from jepsen_tpu.lin import pack_dev, prepare

        t0 = time.monotonic()
        try:
            with obs_trace.span("svc-pack",
                                events=len(history)) as sp:
                pre = pack_dev.prepack(model, history)
                key = bin_key(pre)
                fp = pack_dev.prepack_fingerprint(pre)
                sp.note(bin=key)
        except prepare.UnsupportedHistory as e:
            return None, f"svc-cpu|{e.kind}", None
        with self._stats_lock:
            util.stat_time(self._stats, "bin_pack_s", key,
                           time.monotonic() - t0)
        return pre, key, fp

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except socket.timeout:
                continue   # poll tick: re-check the stop flag
            except OSError:
                return   # listener closed (stop)
            sock.settimeout(None)   # conns block; accept polls
            with self._conns_lock:
                self._conns.add(sock)
            t = threading.Thread(target=self._handle_conn,
                                 args=(sock,), daemon=True,
                                 name="svc-conn")
            t.start()

    def _handle_conn(self, sock) -> None:
        io = SocketIO(sock)
        wlock = threading.Lock()
        alive = {"ok": True}

        def respond(msg: dict) -> None:
            with wlock:
                if not alive["ok"]:
                    self._bump("dropped_responses")
                    return
                try:
                    protocol.send_msg(io, msg)
                except (ConnectionError, OSError):
                    alive["ok"] = False
                    self._bump("dropped_responses")

        try:
            while not self._stop.is_set():
                try:
                    msg = protocol.read_msg(io)
                except (ConnectionError, OSError):
                    break   # client done/dropped; daemon unaffected
                mtype = msg.get("type")
                v = msg.get("v", 1)
                if v != protocol.PROTOCOL_VERSION:
                    # The handshake check (v2 satellite): a version-
                    # skewed client gets ONE readable frame naming both
                    # versions, instead of the opaque codec/KeyError a
                    # new frame family would otherwise produce.
                    self._bump("version_mismatches")
                    respond({"type": "error", "id": msg.get("id"),
                             "error": (
                                 "protocol version mismatch: daemon "
                                 f"speaks v{protocol.PROTOCOL_VERSION}"
                                 f", client sent v{v} — upgrade the "
                                 "client (the version field and the "
                                 "stream-check frames landed in v2)"),
                             "daemon_version":
                                 protocol.PROTOCOL_VERSION})
                    continue
                if mtype == "ping":
                    respond({"type": "pong"})
                elif mtype == "stats":
                    respond({"type": "stats", "stats": self.stats()})
                elif mtype == "shutdown":
                    respond({"type": "ok"})
                    threading.Thread(target=self.stop,
                                     daemon=True).start()
                    break
                elif mtype == "check":
                    self._admit(msg, respond)
                elif mtype == "result-fetch":
                    self._result_fetch(msg, respond)
                elif mtype == "txn-check":
                    self._admit_txn(msg, respond)
                elif mtype == "stream-open":
                    self._stream_open(msg, respond, sock)
                elif mtype == "stream-append":
                    self._stream_append(msg, respond, sock)
                elif mtype == "stream-finalize":
                    self._stream_finalize(msg, respond, sock)
                elif mtype == "stream-abort":
                    self._stream_abort(msg, respond, sock)
                else:
                    respond({"type": "error", "id": msg.get("id"),
                             "error": f"unknown message type {mtype!r}"})
        finally:
            alive["ok"] = False
            with self._conns_lock:
                self._conns.discard(sock)
            self._reap_streams(sock)
            try:
                sock.close()
            except OSError:
                pass

    def _admit(self, msg: dict, respond: Callable) -> None:
        rid = msg.get("id")
        self._bump("submitted")
        try:
            model = protocol.model_by_name(msg.get("model"))
            history = protocol.history_from_wire(
                msg.get("history") or [])
        except (ValueError, TypeError, KeyError) as e:
            self._bump("bad_requests")
            respond({"type": "error", "id": rid, "error": str(e)})
            return
        pre, key, fp = self._pack_admission(model, history)
        if fp is None:
            # Unpackable histories fingerprint randomly per request,
            # so their settles are honestly unfetchable.
            fp = f"unpacked:{rid}:{time.monotonic()}"
        req = Request(rid=rid, model_name=msg.get("model"),
                      model=model, history=history, packed=None,
                      prepack=pre, bin=key, fingerprint=fp,
                      respond=respond)
        self._enqueue_admitted(req, rid, respond, "check",
                               {"model": msg.get("model"),
                                "history": msg.get("history") or []})

    def _admit_txn(self, msg: dict, respond: Callable) -> None:
        """The protocol-v2 ``txn-check`` frame: a list-append txn
        history decided by ``checker.txn_cycles`` under the existing
        supervised per-request fallthrough (txn requests never bin —
        there is no vmapped txn batch program today)."""
        rid = msg.get("id")
        self._bump("submitted")
        self._bump("txn_submitted")
        try:
            history = protocol.history_from_wire(
                msg.get("history") or [])
            kw = _txn_kw(msg)
            if kw["algorithm"] not in ("tpu", "cpu"):
                raise ValueError(
                    f"unknown txn algorithm {kw['algorithm']!r}")
        except (ValueError, TypeError, KeyError) as e:
            self._bump("bad_requests")
            respond({"type": "error", "id": rid, "error": str(e)})
            return
        fp = hashlib.sha256(codec.encode(
            {"history": msg.get("history") or [],
             **{k: list(v) if isinstance(v, tuple) else v
                for k, v in kw.items()}})).hexdigest()
        req = Request(rid=rid, model_name="txn", model=None,
                      history=history, packed=None, bin=_txn_bin(kw),
                      fingerprint=fp, respond=respond, kind="txn",
                      txn_kw=kw, no_batch=True)
        self._enqueue_admitted(req, rid, respond, "txn-check",
                               {"history": msg.get("history") or [],
                                **{k: list(v) if isinstance(v, tuple)
                                   else v for k, v in kw.items()}})

    def _result_fetch(self, msg: dict, respond: Callable) -> None:
        """Journal-aware reconnect: serve the SETTLED verdict for a
        request fingerprint, or an honest structured not-found — a
        client whose submit went indeterminate reads its durable
        answer back without re-deciding (re-submitting would decide
        the history twice). Never a guess: an unsettled or unknown
        fingerprint answers an error naming its status."""
        rid = msg.get("id")
        fp = msg.get("fp")
        self._bump("result_fetches")
        if self._journal is None:
            respond({"type": "error", "id": rid, "status": "unknown",
                     "error": "result-fetch: this daemon runs without "
                              "a journal"})
            return
        if not isinstance(fp, str) or not fp:
            respond({"type": "error", "id": rid, "status": "unknown",
                     "error": "result-fetch: missing fp"})
            return
        status, rec = self._journal.result_for(fp)
        if status == "settled":
            self._bump("result_fetch_hits")
            respond({"type": "verdict", "id": rid, "fp": fp,
                     "fetched": True,
                     "result": protocol.jsonable(
                         (rec or {}).get("result") or {})})
            return
        respond({"type": "error", "id": rid, "fp": fp,
                 "status": status,
                 "error": ("result-fetch: admitted but not yet "
                           "settled — retry" if status == "pending"
                           else "result-fetch: unknown fingerprint")})

    def _enqueue_admitted(self, req: Request, rid, respond: Callable,
                          journal_kind: str, payload: dict) -> None:
        with self._stats_lock:
            admit = self._inflight < self.bound
            if admit:
                self._inflight += 1
        if not admit:
            # Backpressure, not buffering: the client learns NOW that
            # the daemon is at capacity (the check never started, so
            # retrying later is sound).
            self._bump("overloads")
            respond({"type": "error", "id": rid,
                     "error": f"overload: {self.bound} requests in "
                              f"flight (bound)"})
            return
        # Journal BEFORE queueing: once the request can be decided, a
        # crash can no longer lose it (the durability ordering the
        # restart-recovery test rests on).
        if self._journal is not None and not self._crashed:
            try:
                req.journal_seq = self._journal.admit(
                    journal_kind, req.fingerprint, payload)
                self._bump("journal_appends")
            except OSError:
                self._bump("journal_errors")
        self._queue.put(req)

    # --- stream-check sessions (doc/streaming.md) ---------------------------

    def _stream_ckpt_path(self, sid: str) -> str:
        """Per-sid frontier checkpoint. ``JEPSEN_TPU_STREAM_CKPT`` is
        the BASE path: each daemon session checkpoints to
        ``<base>.<sid>.npz`` (sessions must not share one file — the
        fingerprint gate would reject every resume), so a reaped or
        crashed session's carried frontier survives into the journal
        replay: re-adopting the sid re-feeds the journaled appends,
        and the checkpoint fast-forwards them. Empty string = no
        checkpointing (the StreamChecker falsy contract)."""
        base = os.environ.get("JEPSEN_TPU_STREAM_CKPT", "")
        return f"{base}.{sid}.npz" if base else ""

    def _stream_open(self, msg: dict, respond: Callable, sock) -> None:
        from jepsen_tpu.stream import StreamChecker

        rid = msg.get("id")
        want_sid = msg.get("session")   # re-adopt a journaled session
        try:
            model = protocol.model_by_name(msg.get("model"))
        except (ValueError, TypeError) as e:
            respond({"type": "error", "id": rid, "error": str(e)})
            return
        jrec = None
        with self._streams_lock:
            if len(self._streams) >= self.stream_bound:
                self._bump("stream_overloads")
                respond({"type": "error", "id": rid,
                         "error": f"stream overload: "
                                  f"{self.stream_bound} sessions open "
                                  f"(bound)"})
                return
            if want_sid is not None:
                # Re-adoption: the sid must be journaled, still open,
                # same model, and not LIVE (a live session is owned by
                # its connection — no cross-connection capture).
                jrec = (self._journal.stream_sessions().get(want_sid)
                        if self._journal is not None else None)
                if want_sid in self._streams or jrec is None \
                        or jrec.get("model") != msg.get("model"):
                    respond({"type": "error", "id": rid,
                             "error": "unknown stream session"})
                    return
                sid = want_sid
            else:
                self._stream_seq += 1
                sid = f"s{self._stream_seq}-{os.urandom(3).hex()}"
            # min_rows=1: over the wire the CLIENT owns the increment
            # windowing — every append is one increment, so the state
            # reply always reflects the appended ops.
            sess = StreamSession(
                sid, msg.get("model"),
                StreamChecker(model, min_rows=1,
                              checkpoint=self._stream_ckpt_path(sid),
                              view_name=f"stream-{sid}",
                              defer=self.stream_bins), sock)
            self._streams[sid] = sess
        if jrec is not None:
            # Re-feed the journaled appends on the worker (the
            # per-sid checkpoint fast-forwards the re-fed prefix, so
            # this costs host-side packing, not re-checking).
            def refeed():
                last = sess.checker.status()
                for ops in jrec["appends"]:
                    last = sess.checker.append(
                        protocol.history_from_wire(ops))
                if sess.checker.defer:
                    # Deferred appends only settle; bring the check
                    # current so the re-adopt reply carries the same
                    # state a non-deferred session would report.
                    last = sess.checker.drive()
                return last
            outcome, r = self._stream_run(sess, refeed)
            if outcome != "ok":
                self._drop_stream(sid)
                respond({"type": "error", "id": rid, "error": r})
                return
            self._bump("stream_readopts")
            respond({"type": "stream-opened", "id": rid,
                     "session": sid, "resumed": True,
                     "replayed_appends": len(jrec["appends"]),
                     **protocol.jsonable(r)})
            return
        self._journal_stream("stream-open", sid,
                             model=msg.get("model"))
        self._bump("stream_opens")
        respond({"type": "stream-opened", "id": rid, "session": sid})

    def _journal_stream(self, kind: str, sid: str, **fields) -> None:
        if self._journal is None or self._crashed:
            return
        try:
            self._journal.stream_event(kind, sid, **fields)
        except OSError:
            self._bump("journal_errors")

    def _get_stream(self, msg: dict, sock) -> StreamSession | None:
        with self._streams_lock:
            sess = self._streams.get(msg.get("session"))
        # Connection-owned: a foreign session id answers exactly like
        # an unknown one (no cross-connection probing).
        return sess if sess is not None and sess.sock is sock else None

    def _drop_stream(self, sid: str) -> None:
        with self._streams_lock:
            sess = self._streams.pop(sid, None)
        if sess is not None:
            sess.checker.release_view()

    def _reap_streams(self, sock) -> None:
        with self._streams_lock:
            dead = [s for s in self._streams.values()
                    if s.sock is sock]
            for s in dead:
                del self._streams[s.sid]
        for s in dead:
            s.checker.release_view()
        if dead:
            self._bump("stream_reaped", len(dead))

    def _stream_run(self, sess: StreamSession, fn: Callable):
        """Run session work on a WORKER thread (workers own the
        device; stream increments must queue behind batches, not race
        them), blocking the connection handler until done or deadline.
        The session lock serializes the checker across the pool: a
        job whose reply deadline expired may still be RUNNING on its
        worker, and the next job for the same session must wait for
        it, not interleave with it. Returns (outcome, value):
        ("ok", r) | ("error", reason)."""
        done = threading.Event()
        box: dict = {}

        def job():
            try:
                with sess.lock:
                    box["r"] = fn()
            except Exception as e:  # noqa: BLE001 - reported, below
                box["e"] = e
            finally:
                done.set()

        # Route by the session's stream-bin key: every job for one
        # model family lands on one device (its compiled programs
        # live there), via the same placement policy as check bins.
        self._dispatch(stream_bin(sess.model_name), ("stream", job))
        if not done.wait(self.deadline_s):
            # The job still runs (the worker serializes this session's
            # work), only this REPLY gives up — same currency as the
            # per-request deadline.
            return "error", (f"stream increment exceeded the "
                             f"{self.deadline_s:.0f}s deadline")
        if "e" in box:
            return "error", f"stream session error: {box['e']!r}"
        return "ok", box.get("r")

    def _stream_append(self, msg: dict, respond: Callable, sock) -> None:
        sess = self._get_stream(msg, sock)
        if sess is None:
            respond({"type": "error", "session": msg.get("session"),
                     "error": "unknown stream session"})
            return
        try:
            ops = protocol.history_from_wire(msg.get("ops") or [])
        except (TypeError, KeyError) as e:
            respond({"type": "error", "session": sess.sid,
                     "error": f"bad ops: {e!r}"})
            return
        self._bump("stream_appends")
        sess.appends += 1
        # Journal BEFORE the increment runs: a crash mid-increment
        # replays the append into the re-adopted session (the per-sid
        # checkpoint makes a re-fed settled prefix cheap).
        self._journal_stream("stream-append", sess.sid,
                             ops=msg.get("ops") or [])
        if sess.checker.defer and not self._stop.is_set():
            # svc-stream bins: feed + settle host-side NOW (handler
            # thread — host packing parallelizes across connections),
            # then route the pending increment through the scheduler's
            # bins so concurrent sessions sharing a shape batch into
            # one vmapped program on the worker pool.
            try:
                with sess.lock:
                    sess.checker.append(ops)
            except Exception as e:  # noqa: BLE001 - reported on wire
                respond({"type": "error", "session": sess.sid,
                         "error": f"stream session error: {e!r}"})
                return
            item = _StreamIncr(sess=sess,
                               bin=stream_bin(sess.model_name))
            self._queue.put(item)
            if not item.done.wait(self.deadline_s):
                respond({"type": "error", "session": sess.sid,
                         "error": f"stream increment exceeded the "
                                  f"{self.deadline_s:.0f}s deadline"})
                return
            if item.error is not None:
                respond({"type": "error", "session": sess.sid,
                         "error": item.error})
                return
            respond({"type": "stream-state", "session": sess.sid,
                     **protocol.jsonable(item.reply or {})})
            return
        outcome, r = self._stream_run(sess,
                                      lambda: sess.checker.append(ops))
        if outcome != "ok":
            respond({"type": "error", "session": sess.sid, "error": r})
            return
        respond({"type": "stream-state", "session": sess.sid,
                 **protocol.jsonable(r)})

    def _stream_finalize(self, msg: dict, respond: Callable,
                         sock) -> None:
        sess = self._get_stream(msg, sock)
        if sess is None:
            respond({"type": "error", "session": msg.get("session"),
                     "error": "unknown stream session"})
            return
        outcome, r = self._stream_run(sess, sess.checker.finalize)
        self._drop_stream(sess.sid)   # slot freed either way
        self._journal_stream("stream-close", sess.sid, how="finalize")
        self._bump("stream_finalizes")
        if outcome != "ok":
            respond({"type": "error", "session": sess.sid, "error": r})
            return
        respond({"type": "verdict", "id": sess.sid,
                 "result": protocol.jsonable(r)})

    def _stream_abort(self, msg: dict, respond: Callable, sock) -> None:
        sess = self._get_stream(msg, sock)
        if sess is None:
            respond({"type": "error", "session": msg.get("session"),
                     "error": "unknown stream session"})
            return
        # Through the worker like append/finalize: StreamChecker is not
        # thread-safe, and an in-flight increment may be running there.
        self._stream_run(sess, sess.checker.abort)
        self._drop_stream(sess.sid)
        self._journal_stream("stream-close", sess.sid, how="abort")
        self._bump("stream_aborts")
        respond({"type": "ok", "session": sess.sid})

    # --- scheduler ----------------------------------------------------------

    def _scheduler_loop(self) -> None:
        oldest: dict[str, float] = {}
        poll = max(0.002, min(0.05, self.flush_s / 2))
        while True:
            stopping = self._stop.is_set()
            req = None
            try:
                req = self._queue.get(timeout=poll)
            except queue.Empty:
                if stopping:
                    break
            if req is not None:
                with self._bins_lock:
                    self._bins.setdefault(req.bin, []).append(req)
                oldest.setdefault(req.bin, time.monotonic())
            now = time.monotonic()
            flush: list[tuple[str, list[Request]]] = []
            with self._bins_lock:
                for key, reqs in list(self._bins.items()):
                    if not reqs:
                        continue
                    if len(reqs) >= self.max_batch or stopping or \
                            now - oldest.get(key, now) >= self.flush_s:
                        flush.append((key, reqs[:self.max_batch]))
                        rest = reqs[self.max_batch:]
                        if rest:
                            self._bins[key] = rest
                            oldest[key] = now
                        else:
                            del self._bins[key]
                            oldest.pop(key, None)
            for key, batch in flush:
                self._dispatch(key, batch)
            if not stopping:
                self._supervise_workers()
            self._write_stats_snapshot()
        if self._crashed:
            return   # SIGKILL semantics: nothing drains, nothing
            #          settles — the journal replay owns recovery
        # Drain-and-stop: everything still queued flushes to the
        # workers, THEN the sentinels (stop() enqueues them after
        # joining this thread).
        with self._bins_lock:
            for key, reqs in self._bins.items():
                if reqs:
                    self._dispatch(key, list(reqs))
            self._bins.clear()
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            self._dispatch(req.bin, [req])

    def _dispatch(self, key: str, item) -> None:
        """Route one flushed work item to a worker slot via the
        placement policy (bin -> device affinity, least-loaded
        spill). Trivial at workers=1: the single slot takes
        everything and the policy is never load-consulted."""
        if len(self._workers) <= 1:
            self._workers[0].q.put(item)
            return
        depths = [w.q.qsize() + (1 if w.busy is not None else 0)
                  for w in self._workers]
        slot, route = self._placement.place(key, depths)
        if route == "spill":
            self._bump("placement_spills")
        self._workers[slot].q.put(item)

    # --- worker pool --------------------------------------------------------

    def _consume_worker_kill(self) -> bool:
        """The worker-kill chaos hook (``inject_worker_kill()`` /
        ``JEPSEN_TPU_SERVICE_KILL``): True means THIS worker thread
        must die now, with its work in hand — the supervisor's
        detection/requeue/respawn path is what's under test."""
        with self._kill_lock:
            if self._kill_armed > 0:
                self._kill_armed -= 1
                return True
            return False

    def inject_worker_kill(self, n: int = 1) -> None:
        """Arm the chaos hook: the next ``n`` work items each kill
        their worker thread mid-item."""
        with self._kill_lock:
            self._kill_armed += n

    def _consume_device_loss(self) -> bool:
        """The device-loss chaos hook (``inject_device_loss()`` /
        ``JEPSEN_TPU_SERVICE_DEVLOSS``): True means THIS worker's
        device just died — the pool must re-place its bins onto
        surviving devices with zero lost or flipped verdicts."""
        with self._kill_lock:
            if self._devloss_armed > 0:
                self._devloss_armed -= 1
                return True
            return False

    def inject_device_loss(self, n: int = 1) -> None:
        """Arm the chaos hook: the next ``n`` work items each lose
        their worker's DEVICE (the worker dies with the item in hand,
        its bin homes re-place onto survivors, and the respawn binds
        to the least-loaded surviving device)."""
        with self._kill_lock:
            self._devloss_armed += n

    def _note_device_loss(self, state: _WorkerState) -> None:
        state.device_lost = True
        if state.device_ix is not None:
            self._lost_devices.add(state.device_ix)
        re_homed = self._placement.forget_slot(state.slot)
        self._bump("device_losses")
        obs_metrics.REGISTRY.event(
            "device-loss", worker=state.wid, slot=state.slot,
            device=state.device_ix, re_homed=len(re_homed))

    def _worker_loop(self, state: _WorkerState) -> None:
        while True:
            if state.abandoned or self._crashed:
                return
            batch = state.q.get()
            if batch is None:
                return
            # busy_since BEFORE busy: the supervisor reads (busy,
            # busy_since) without a lock, and the reverse order lets a
            # tick pair the fresh item with the PREVIOUS item's stale
            # timestamp — an instant false wedge.
            state.busy_since = time.monotonic()
            state.busy = batch
            # The kill hook is inert during drain-and-stop: the
            # supervisor that would requeue the in-hand batch exits
            # with the scheduler, so a drain-time kill would strand
            # (not requeue) it — an armed event just lands on the
            # drain instead.
            if not self._stop.is_set() and self._consume_worker_kill():
                # Simulated worker death: the thread exits abruptly
                # with the batch IN HAND (state.busy still set) —
                # exactly the state a real thread death leaves, which
                # the supervisor must detect, requeue once, respawn.
                self._bump("worker_kills")
                return
            if not self._stop.is_set() and self._consume_device_loss():
                # Simulated DEVICE loss (chip gone): mark the device
                # dead, forget this slot's bin homes so they re-place
                # onto survivors, then die with the batch in hand —
                # the proven death/requeue/respawn path carries the
                # work, and the respawn rebinds off the lost device.
                self._note_device_loss(state)
                return
            t_item = time.monotonic()
            c0 = _compile_meter_snapshot().get("xla_compiles", 0)
            try:
                if state.device is not None:
                    import jax

                    # Thread-local device binding: every dispatch this
                    # item runs lands on this worker's device, which
                    # is the cache the placement policy is placing.
                    with jax.default_device(state.device):
                        self._run_item(batch)
                else:
                    self._run_item(batch)
            except Exception:  # noqa: BLE001 - the daemon must survive
                self._bump("worker_errors")
                import traceback

                # Only the requests NOT already answered mid-batch:
                # _finish guards double-finish too, but re-answering an
                # answered connection would desync its synchronous
                # client (an unsolicited frame becomes the next
                # submit's "verdict").
                if isinstance(batch, list):
                    for req in batch:
                        if isinstance(req, Request) and not req.done:
                            self._finish(req, {
                                "valid?": "unknown",
                                "error": "service worker error: "
                                         + traceback.format_exc(
                                             limit=3)},
                                batch_n=len(batch),
                                t0=time.monotonic())
            finally:
                state.busy = None
                state.items += 1
                state.busy_s += time.monotonic() - t_item
                state.compiles += max(
                    0, _compile_meter_snapshot().get(
                        "xla_compiles", 0) - c0)

    def _run_item(self, batch) -> None:
        if isinstance(batch, tuple) and batch and batch[0] == "stream":
            # Stream-session job (already exception-proofed by
            # _stream_run's wrapper): runs on a worker thread so
            # increments serialize with batches on the device, never
            # race them.
            batch[1]()
            return
        if batch and isinstance(batch[0], _StreamIncr):
            self._process_stream_batch(batch)
            return
        self._process_batch(batch)

    def _touch_worker(self) -> None:
        """Refresh the calling worker's progress clock. The wedge
        backstop bounds progress-FREE time, not whole work items: one
        batch legitimately runs many sequential supervised dispatches
        (a decline-heavy bin falls through to per-request checks), so
        each started single and each answered request resets the
        clock — only a genuine hang accumulates."""
        t = threading.current_thread()
        for st in self._workers:
            if st.thread is t:
                st.busy_since = time.monotonic()
                return

    def _supervise_workers(self) -> None:
        """One scheduler-tick pass over the pool: a DEAD worker (the
        kill hook, or a bug past the loop's catch-all) or a WEDGED one
        (busy past the worker deadline — a non-dispatch hang the
        in-batch watchdog can't see) is abandoned; its in-hand work is
        requeued ONCE (per-request ``attempts`` caps it — the PR 6
        fault-requeue promise promoted to per-worker), the bin shape
        is ledger-recorded, and a replacement spawns. The daemon never
        dies with a worker; the ``done`` guard means a late result
        from an abandoned-but-alive worker can never double-answer."""
        now = time.monotonic()
        for i, st in enumerate(self._workers):
            alive = st.thread is not None and st.thread.is_alive()
            wedged = (alive and st.busy is not None
                      and now - st.busy_since > self.worker_deadline)
            if alive and not wedged:
                continue
            batch = st.busy
            st.busy = None
            st.abandoned = True
            kind = "wedge" if wedged else "death"
            self._bump("worker_wedges" if wedged else "worker_deaths")
            obs_metrics.REGISTRY.event("worker-" + kind,
                                       worker=st.wid)
            if wedged and st.thread is not None:
                self._abandoned.append(st.thread)
            if batch is not None:
                self._requeue_worker_batch(batch, kind, st.q)
            self._bump("worker_respawns")
            self._workers[i] = self._spawn_worker(st.slot, inherit=st)

    def _requeue_worker_batch(self, batch, kind: str,
                              q: queue.Queue) -> None:
        from jepsen_tpu.lin import supervise

        if isinstance(batch, tuple) and batch and batch[0] == "stream":
            if kind == "wedge":
                # The job IS the hang, still running on the abandoned
                # thread (it holds the session lock): re-putting it
                # would just wedge the replacement worker too. The
                # client already got its deadline error; drop it.
                self._bump("stream_drops")
                return
            # A DEAD worker never started the job (jobs are
            # exception-proofed; only the kill hooks — which fire
            # BEFORE the job runs — kill a worker): re-put it on the
            # slot queue (the respawn inherits it), and the waiting
            # connection handler picks up the late result within its
            # deadline.
            q.put(batch)
            self._bump("stream_requeues")
            return
        if batch and isinstance(batch[0], _StreamIncr):
            live = [it for it in batch if not it.done.is_set()]
            if kind == "wedge":
                # Like the solo stream wedge: the batch may still be
                # running on the abandoned thread (holding session
                # locks) — re-putting would wedge the replacement.
                # The handlers answer their own deadlines.
                self._bump("stream_drops", len(live))
                return
            if live:
                # Dead worker: unanswered items re-run on the
                # replacement (increment_job recomputes from session
                # state, so a re-run never double-commits).
                q.put(live)
                self._bump("stream_requeues", len(live))
            return
        supervise.record_fault(batch[0].bin,
                               "wedge" if kind == "wedge" else "fault",
                               f"service worker {kind}")
        pending = [r for r in batch if not r.done]
        if pending:
            self._requeue_or_fail(
                pending, RuntimeError(f"service worker {kind}"),
                time.monotonic())

    def _materialize_admitted(self, reqs: list[Request]) -> None:
        """Device-resident pack of one flushed wave (doc/service.md §
        Device packing): every prepacked request paints its grids
        HERE, on the worker thread — same-bucket lanes ride ONE
        vmapped ``pack-dev`` dispatch on this worker's placed device,
        deduped by fingerprint (a resubmitted history packs once).
        Supervised with an honest numpy fallback: a wedged, faulted,
        quarantined, or static-flagged pack program costs pack wall,
        never a verdict."""
        from jepsen_tpu.lin import pack_dev

        todo: dict[str, list[Request]] = {}
        for r in reqs:
            if r.packed is None and r.prepack is not None:
                todo.setdefault(r.fingerprint, []).append(r)
        if not todo:
            return
        t0 = time.monotonic()
        packs = pack_dev.materialize_batch(
            [rs[0].prepack for rs in todo.values()],
            stats=self._supervise_stats())
        for rs, p in zip(todo.values(), packs):
            for r in rs:
                r.packed, r.prepack = p, None
        with self._stats_lock:
            util.stat_time(self._stats, "bin_pack_s", reqs[0].bin,
                           time.monotonic() - t0)

    def _process_batch(self, reqs: list[Request]) -> None:
        from jepsen_tpu.lin import supervise

        t0 = time.monotonic()
        self._materialize_admitted(reqs)
        singles: list[Request] = []
        batchable: list[Request] = []
        for r in reqs:
            if r.no_batch or r.packed is None \
                    or r.packed.kernel is None:
                singles.append(r)
            else:
                batchable.append(r)

        if len(batchable) >= 2:
            # Duplicate fingerprints (same history resubmitted, e.g. a
            # retried client) decide once and fan out. The batch is
            # keyed by FINGERPRINT, never by the client-chosen rid:
            # two clients' auto-ids collide routinely (each counts
            # 1, 2, ...), and a rid-keyed dict would silently drop one
            # request on the floor.
            by_fp: dict[str, list[Request]] = {}
            for r in batchable:
                by_fp.setdefault(r.fingerprint, []).append(r)
            # Already-packed values: the admission tier prepacked and
            # the wave above painted the grids (device-batched), so
            # the batch program must not re-pack — try_check_batch
            # accepts PackedHistory values as-is.
            subs = {fp: reqs_fp[0].packed
                    for fp, reqs_fp in by_fp.items()}
            self._bump("dedup_hits", len(batchable) - len(by_fp))
            pad_ids = []
            if pad_pow2() and len(subs) > 1:
                # Pad the key axis to the next power of two so each
                # (shape, K-bucket) vmapped program compiles once —
                # zero retrace across varying bin occupancies.
                want = 1 << (len(subs) - 1).bit_length()
                proto_hist = next(iter(subs.values()))
                for i in range(want - len(subs)):
                    pad_ids.append(f"__svc_pad_{i}__")
                    subs[pad_ids[-1]] = proto_hist
                self._bump("pad_keys", len(pad_ids))
            declines: list = []
            # run_guarded's deadline is scale x the base dispatch
            # deadline; express the service's per-request deadline in
            # that currency so the batch site honors the same budget.
            scale = self.deadline_s / max(supervise.base_deadline_s(),
                                          1e-6)
            outcome, res = supervise.run_guarded(
                "service-batch", reqs[0].bin,
                lambda: self._batch(reqs[0].model, subs, declines),
                scale=scale, stats=self._supervise_stats())
            if outcome == "ok":
                res = res or {}
                covered = 0
                for fp, reqs_fp in by_fp.items():
                    if fp in res:
                        covered += 1
                        for r in reqs_fp:
                            self._finish(r, res[fp],
                                         batch_n=len(subs), t0=t0)
                    else:
                        singles.extend(reqs_fp)
                if covered:
                    with self._stats_lock:
                        util.stat_bump(self._stats, "batches")
                        util.stat_bump(self._stats, "batched_requests",
                                       covered)
                        self._stats["max_occupancy"] = max(
                            self._stats.get("max_occupancy", 0),
                            covered)
                        util.stat_time(self._stats, "bin_decide_s",
                                       reqs[0].bin,
                                       time.monotonic() - t0)
                for d in declines:
                    with self._stats_lock:
                        util.stat_bump(self._stats["decline_axes"],
                                       d.axis, len(d.keys) or 1)
            elif outcome == "wedge":
                # The watchdog already retried inside run_guarded; a
                # still-wedged batch reports honestly rather than
                # tarpitting the queue behind a second full deadline.
                for r in batchable:
                    self._finish(r, {"valid?": "unknown",
                                     "overflow": "wedge",
                                     "error": f"service-batch: {res}"},
                                 batch_n=len(subs), t0=t0)
            else:   # fault — requeue once as singles, off the batch
                # (run_guarded already noted the fault event and
                # recorded the bin shape in the quarantine ledger.)
                self._requeue_or_fail(batchable, res, t0)
        else:
            singles.extend(batchable)

        for r in singles:
            self._check_single(r)

    def _batch(self, model, subs: dict, declines: list):
        from jepsen_tpu.lin import batched

        fn = self._batch_fn or batched.try_check_batch
        res = fn(model, subs, declines=declines)
        res = dict(res or {})
        for k in list(res):
            if isinstance(k, str) and k.startswith("__svc_pad_"):
                del res[k]
        return res

    def _process_stream_batch(self, items: list) -> None:
        """One flushed svc-stream bin: collect the member sessions'
        pending increments and decide them as ONE vmapped
        carried-frontier program (``lin.batched.try_stream_batch``),
        committing each clean lane; a declined/dead lane (or a
        wedged/faulted batch program) falls back to the session's solo
        supervised path (``drive()``) from the SAME uncommitted
        frontier — identical verdicts, full witness machinery. Every
        item answers its blocked connection handler via ``done``."""
        from jepsen_tpu.lin import batched, supervise

        t0 = time.monotonic()
        pending = [it for it in items if not it.done.is_set()]
        # One global lock order (sorted sid) across the batch: solo
        # stream jobs take single session locks, so ordered multi-lock
        # acquisition here cannot deadlock against them.
        pending.sort(key=lambda it: it.sess.sid)
        locked: list = []
        try:
            for it in pending:
                it.sess.lock.acquire()
                locked.append(it.sess.lock)
            jobs, carriers = [], []
            for it in pending:
                job = it.sess.checker.increment_job()
                if job is not None:
                    jobs.append(job)
                    carriers.append(it)
            if len(jobs) >= 2:
                self._touch_worker()
                scale = self.deadline_s / max(
                    supervise.base_deadline_s(), 1e-6)
                fn = self._stream_batch_fn or batched.try_stream_batch
                outcome, res = supervise.run_guarded(
                    "service-stream", pending[0].bin,
                    lambda: fn(jobs), scale=scale,
                    stats=self._supervise_stats())
                dt = time.monotonic() - t0
                if outcome == "ok" and isinstance(res, list) \
                        and len(res) == len(jobs):
                    lanes = 0
                    for it, job, r in zip(carriers, jobs, res):
                        if isinstance(r, dict):
                            lanes += 1
                            it.sess.checker.commit_increment(
                                r, row0=job["row0"],
                                dt=dt / len(jobs))
                        else:
                            if isinstance(r, batched.Decline):
                                with self._stats_lock:
                                    util.stat_bump(
                                        self._stats["decline_axes"],
                                        r.axis)
                            it.sess.checker.drive()
                    if lanes:
                        with self._stats_lock:
                            util.stat_bump(self._stats,
                                           "stream_batches")
                            util.stat_bump(self._stats,
                                           "stream_batched_increments",
                                           lanes)
                            self._stats["stream_batch_max_occupancy"] \
                                = max(self._stats.get(
                                    "stream_batch_max_occupancy", 0),
                                    lanes)
                else:
                    # Wedge/fault on the shared program: each session
                    # falls back solo (its own supervised ladder) —
                    # the batch program never poisons a session.
                    self._bump("stream_batch_fallbacks")
                    for it in carriers:
                        it.sess.checker.drive()
            elif len(jobs) == 1:
                self._bump("stream_solo_increments")
                carriers[0].sess.checker.drive()
            for it in pending:
                out = it.sess.checker.status()
                v = it.sess.checker.verdict
                if v is not None:
                    out["result"] = v
                it.reply = out
        except Exception as e:  # noqa: BLE001 - answer, never strand
            self._bump("stream_batch_errors")
            for it in pending:
                if it.reply is None:
                    it.error = f"stream session error: {e!r}"
        finally:
            for lk in locked:
                lk.release()
            for it in pending:
                it.done.set()

    def _check_single(self, req: Request) -> None:
        from jepsen_tpu.lin import supervise

        t0 = time.monotonic()
        if req.packed is None and req.prepack is not None:
            # A single that skipped the wave (drain-time requeue,
            # direct-call tests): materialize its grids now.
            self._materialize_admitted([req])
        self._bump("single_requests")
        self._touch_worker()   # each single gets its own wedge budget

        def thunk():
            if req.kind == "txn":
                # The txn-check frame: checker.txn_cycles under this
                # same supervised per-request fallthrough (wedge ->
                # honest unknown, fault -> requeue once; the txn
                # engine's own tier ladder rides inside the thunk).
                from jepsen_tpu import checker as checker_ns

                ck = checker_ns.txn_cycles(**req.txn_kw)
                return ck.check(None, None, req.history, {})
            if self._check_fn is not None:
                return self._check_fn(req.packed, req.model,
                                      req.history)
            from jepsen_tpu import lin

            if req.packed is None:
                # Unpackable shape (e.g. window past the device
                # bitset): lin.analysis routes it to the unbounded
                # host search.
                return lin.analysis(req.model, req.history)
            return lin.device_check_packed(req.packed)

        try:
            r = supervise.call("service-check", thunk,
                               deadline_s=self.deadline_s, retries=0,
                               stats=self._supervise_stats(),
                               shape=req.bin)
            self._finish(req, r, batch_n=1, t0=t0)
        except supervise.WedgedDispatch as e:
            self._bump("wedged_requests")
            supervise.record_fault(req.bin, "wedge")
            self._finish(req, {"valid?": "unknown",
                               "overflow": "wedge",
                               "error": str(e)}, batch_n=1, t0=t0)
        except (RuntimeError, OSError) as e:
            supervise.note_fault(self._supervise_stats(),
                                 "service-check", repr(e))
            supervise.record_fault(req.bin, "fault", repr(e))
            self._requeue_or_fail([req], e, t0)

    def _requeue_or_fail(self, reqs: list[Request], err, t0) -> None:
        """The fault policy: each in-flight request rides ONE requeue
        (as a single, off the suspect batch program); a second fault
        fails honestly. The daemon never dies with the worker."""
        for r in reqs:
            if r.attempts < _REQUEUE_MAX:
                r.attempts += 1
                r.no_batch = True
                self._bump("requeues")
                if self._stop.is_set():
                    # Drain-and-stop: the scheduler that would pick
                    # the requeue off the admission queue is gone (or
                    # going) — run the retry inline so the one-retry
                    # promise holds for in-flight work at shutdown.
                    self._check_single(r)
                else:
                    # Still in flight (admission already counted it),
                    # so the requeue consumes no fresh admission slot.
                    self._queue.put(r)
            else:
                self._bump("honest_fails")
                self._finish(r, {"valid?": "unknown",
                                 "overflow": "fault",
                                 "error": f"fault (after requeue): "
                                          f"{err!r}"},
                             batch_n=1, t0=t0)

    def _supervise_stats(self) -> dict:
        # supervise._note_event writes watchdog_trips/faults/
        # supervise_events keys; share the service stats dict under
        # the lock-free GIL-atomic increments it uses.
        return self._stats

    def _finish(self, req: Request, result: dict, *, batch_n: int,
                t0: float) -> None:
        # Atomic test-and-set on done: with a worker POOL, a requeued
        # request's replacement decide can race a late result from the
        # abandoned worker — both must never answer (or account) the
        # same request. A "crashed" daemon answers nothing at all.
        with self._stats_lock:
            if req.done or self._crashed:
                return
            req.done = True
        self._touch_worker()   # an answered request is worker progress
        # Settle the journal BEFORE the wire reply: the settle record
        # is the durable answer (at-least-once settled; the done flag
        # keeps the live reply exactly-once).
        if self._journal is not None and req.journal_seq is not None:
            try:
                self._journal.settle(req.journal_seq, req.fingerprint,
                                     protocol.jsonable(result))
                self._bump("journal_settles")
            except OSError:
                self._bump("journal_errors")
        now = time.monotonic()
        wait = t0 - req.t_enqueue
        valid = result.get("valid?")
        self._bump("decided")
        self._bump("verdict_true" if valid is True else
                   "verdict_false" if valid is False else
                   "verdict_unknown")
        with self._stats_lock:
            self._inflight -= 1
            util.stat_bump(self._stats["bin_requests"], req.bin)
            self._stats["queue_wait_s_sum"] = round(
                self._stats.get("queue_wait_s_sum", 0.0) + wait, 4)
            self._stats["decide_s_sum"] = round(
                self._stats.get("decide_s_sum", 0.0) + (now - t0), 4)
        self._note_latency(now - req.t_enqueue)
        # One span per request lifecycle (admit -> bin -> batch ->
        # decide): retro-recorded here because the path crosses the
        # handler, scheduler, and worker threads.
        obs_trace.complete("svc-request", req.t_enqueue,
                           now - req.t_enqueue, bin=req.bin,
                           verdict=str(valid), batch_n=batch_n,
                           queue_wait_s=round(wait, 4),
                           decide_s=round(now - t0, 4))
        req.respond({"type": "verdict", "id": req.rid,
                     "result": protocol.jsonable(result),
                     "timings": {"queue_wait_s": round(wait, 4),
                                 "decide_s": round(now - t0, 4),
                                 "batch_n": batch_n,
                                 "attempts": req.attempts}})


# --- process-wide XLA compile meter ----------------------------------------
# The service's whole value proposition is amortizing compiles. The
# meter is the SHARED util wrap (util.install_compile_meter) — one
# backend_compile wrap counting for the quick-tier enforcement
# (tests/conftest.py), these service stats, and the obs registry,
# instead of the three divergent private copies that predate it.


def _install_compile_meter() -> None:
    util.install_compile_meter()


def _compile_meter_snapshot() -> dict:
    return util.compile_meter()


def _pack_meter_snapshot() -> dict:
    """Process-wide host-pack meter (the compile-meter convention):
    seconds this process spent packing histories — lin prepare passes
    + stream settled-row increments + txn version-order joins — and
    the packer mode that served the last pack. Best-effort: stats()
    must never fail because a pack counter could not be read."""
    try:
        from jepsen_tpu.lin import pack_dev as _pack_dev
        from jepsen_tpu.lin import prepare as _prep
        from jepsen_tpu.txn import pack as _txn_pack

        ps = _prep.pack_stats()
        ts = _txn_pack.pack_stats()
        ds = _pack_dev.dev_stats()
        return {"pack_seconds": round(
                    ps["prepare_s"] + ps["incr_s"] + ts["pack_s"], 3),
                "pack_calls": (ps["prepare_calls"] + ps["incr_calls"]
                               + ts["pack_calls"]),
                "pack_mode": ps["mode"],
                "pack_dev_packs": ds["dev_packs"],
                "pack_dev_lanes": ds["dev_lanes"],
                "pack_dev_seconds": round(ds["dev_pack_s"], 3),
                "pack_dev_fallbacks": ds["host_fallbacks"]}
    except Exception:  # noqa: BLE001 - observability only
        return {}


def serve_checker(host: str = "127.0.0.1", port: int | None = None,
                  **kw) -> None:
    """Run the daemon until interrupted (the ``serve-checker`` CLI)."""
    svc = CheckerService(host, port, **kw).start()
    print(f"checker daemon on {svc.host}:{svc.port} "
          f"(queue bound {svc.bound}, flush "
          f"{svc.flush_s * 1000:.0f} ms, max batch {svc.max_batch})",
          flush=True)
    try:
        svc.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        svc.stop()
