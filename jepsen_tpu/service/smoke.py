"""`make serve-smoke`: start daemon -> submit -> assert -> shutdown.

A FRESH-process proof (the dryrun_multichip contract: it forces the
CPU platform itself, before any backend init) that the checker daemon
round-trips real verdicts: an ephemeral-port daemon on the 8-device
CPU mesh, three histories of different models submitted over a real
socket, verdicts asserted against the CPU oracle, clean shutdown.
Prints one JSON result line and exits 0/1 — timeout-guarded by the
Makefile so a wedge cannot hold the shell.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> int:
    t_start = time.time()
    # CPU mesh BEFORE any jax backend init (CLAUDE.md: the TPU plugin
    # force-selects its platform; the smoke must never take the chip).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from jepsen_tpu import models as m
    from jepsen_tpu.lin import cpu, prepare, synth
    from jepsen_tpu.service.daemon import CheckerService
    from jepsen_tpu.service.protocol import CheckerClient

    svc = CheckerService("127.0.0.1", 0, flush_ms_=20).start()
    out = {"port": svc.port, "checks": []}
    ok = True
    try:
        client = CheckerClient("127.0.0.1", svc.port)
        cases = [
            ("cas-register", m.cas_register(),
             synth.generate_register_history(
                 60, concurrency=4, seed=1, crash_prob=0.05,
                 max_crashes=3)),
            ("register", m.register(),
             synth.corrupt_history(synth.generate_register_history(
                 40, concurrency=3, seed=2, fs=("read", "write")),
                 seed=2)),
            ("mutex", m.mutex(),
             synth.generate_mutex_history(40, concurrency=4, seed=3)),
        ]
        for name, model, h in cases:
            want = cpu.check_packed(prepare.prepare(model, h))["valid?"]
            got = client.submit(name, h)
            rec = {"model": name, "want": want,
                   "got": got.get("valid?"),
                   "analyzer": got.get("analyzer"),
                   "timings": got.get("_timings")}
            out["checks"].append(rec)
            ok = ok and got.get("valid?") == want
        out["stats"] = {k: v for k, v in client.stats().items()
                        if k in ("submitted", "decided", "batches",
                                 "avg_occupancy", "xla_compiles")}
        client.shutdown()
        client.close()
    finally:
        svc.stop()

    # 2-worker placement leg (doc/service.md § Placement): the same
    # oracle-checked cases through a 2-slot pool — bins must HOME
    # (affinity visible in the placement block) with zero flips.
    svc2 = CheckerService("127.0.0.1", 0, flush_ms_=20,
                          workers=2).start()
    try:
        client = CheckerClient("127.0.0.1", svc2.port)
        placed_ok = True
        for name, model, h in cases:
            want = cpu.check_packed(prepare.prepare(model, h))["valid?"]
            got = client.submit(name, h)
            placed_ok = placed_ok and got.get("valid?") == want
        block = client.stats().get("placement", {})
        rec = {"leg": "placement-2w",
               "homes": len(block.get("homes") or {}),
               "placed": block.get("placed"),
               "devices": [w.get("device")
                           for w in block.get("workers", [])],
               "items": [w.get("items")
                         for w in block.get("workers", [])],
               "verdicts_ok": placed_ok,
               "ok": (placed_ok and (block.get("placed") or 0) >= 1
                      and len(block.get("homes") or {}) >= 1
                      and len(block.get("workers", [])) == 2)}
        out["checks"].append(rec)
        ok = ok and rec["ok"]
        client.shutdown()
        client.close()
    finally:
        svc2.stop()
    out["ok"] = ok
    # Cross-run perf ledger (doc/observability.md § Perf ledger): the
    # smoke is an evidence producer; record() never raises, so a
    # ledger failure cannot cost the smoke verdict.
    from jepsen_tpu.obs import ledger as perf_ledger

    perf_ledger.record("serve-smoke", kind="smoke",
                       wall_s=time.time() - t_start, verdict=ok,
                       extra={"stats": out.get("stats")})
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
