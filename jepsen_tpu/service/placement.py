"""Fleet placement policy: bin -> device affinity with least-loaded spill.

The thing being placed is a COMPILED PROGRAM FAMILY, not a work item:
a shape bin's vmapped program compiles once per (device, occupancy
bucket), so the placement that matters is keeping a bin's dispatches
on the device that already holds its XLA executables. The policy is
therefore sticky-first:

- A bin key's FIRST placement homes it on the least-loaded worker
  slot (queue depth + busy flag at placement time), and the key
  sticks to that slot — zero re-compile on the steady state.
- When the home slot's depth exceeds the spill knob
  (``JEPSEN_TPU_SERVICE_SPILL_DEPTH``) AND some other slot is
  strictly less deep, the ONE flush spills to the least-loaded slot
  (latency beats cache warmth past the knob). The home assignment is
  unchanged — the next uncongested flush goes home again.
- ``forget_slot`` (device loss) drops every home on the lost slot, so
  each affected bin re-homes by least-loaded on its next flush — the
  re-place-onto-survivors semantics the chaos leg asserts.

Slots are POSITIONS in the worker pool, not thread identities: a
respawned worker inherits its predecessor's slot, queue, and device,
so homes survive worker deaths (the respawn keeps the device and its
compile cache; only device LOSS re-homes).

The placement covers the wave's PACK as well as its check: the worker
loop wraps each batch in ``jax.default_device(slot device)``, so the
admission tier's deferred batched pack (``lin/pack_dev``,
doc/service.md § Device packing) materializes a bin's tables on the
same device its check program reads them from — placement needs no
extra wiring for it.

Pure host-side bookkeeping — no jax imports, safe at workers=1
(where the daemon never consults it beyond the trivial one-slot
answer).
"""

from __future__ import annotations

import threading

from jepsen_tpu import util


def spill_depth() -> int:
    """Home-queue depth past which a flush spills to the least-loaded
    slot (``JEPSEN_TPU_SERVICE_SPILL_DEPTH``). Depth counts queued
    items plus the in-hand one; negative disables spilling (pure
    affinity)."""
    return util.env_int("JEPSEN_TPU_SERVICE_SPILL_DEPTH", 4)


class Placement:
    """Bin-key -> worker-slot affinity map with least-loaded spill."""

    def __init__(self, n_slots: int,
                 spill_depth_: int | None = None):
        self.n_slots = max(1, n_slots)
        self.spill_depth = spill_depth_ if spill_depth_ is not None \
            else spill_depth()
        self.home: dict[str, int] = {}
        self.placed = 0      # placements answered
        self.homed = 0       # ... that went to the home slot
        self.spills = 0      # ... that spilled off a congested home
        self.re_homes = 0    # home entries dropped by forget_slot
        self._lock = threading.Lock()

    @staticmethod
    def _least_loaded(depths: list[int]) -> int:
        return min(range(len(depths)), key=lambda i: (depths[i], i))

    def place(self, key: str, depths: list[int]) -> tuple[int, str]:
        """Pick the slot for one flush of ``key`` given current
        per-slot depths. Returns ``(slot, route)`` with route one of
        ``"new"`` (first placement, homes here), ``"home"``, or
        ``"spill"`` (home congested; home assignment unchanged)."""
        with self._lock:
            self.placed += 1
            h = self.home.get(key)
            if h is None or h >= len(depths):
                h = self._least_loaded(depths)
                self.home[key] = h
                return h, "new"
            if 0 <= self.spill_depth < depths[h]:
                alt = self._least_loaded(depths)
                if depths[alt] < depths[h]:
                    self.spills += 1
                    return alt, "spill"
            self.homed += 1
            return h, "home"

    def forget_slot(self, slot: int) -> list[str]:
        """Drop every home on ``slot`` (device loss): the affected
        keys re-home by least-loaded on their next placement."""
        with self._lock:
            keys = [k for k, s in self.home.items() if s == slot]
            for k in keys:
                del self.home[k]
            self.re_homes += len(keys)
            return keys

    def snapshot(self) -> dict:
        with self._lock:
            return {"homes": dict(self.home),
                    "placed": self.placed,
                    "homed": self.homed,
                    "spills": self.spills,
                    "re_homes": self.re_homes,
                    "spill_depth": self.spill_depth}
