"""Fleet scaling bench: workers=1 vs workers=8 on mixed daemon traffic.

The tentpole's evidence leg (``cli.py fleet-bench``, folded into
``bench.py service_c30``): ONE seeded mixed workload — check requests
across many distinct shape bins, K concurrent wire stream sessions,
and a txn-check minority — driven twice through an in-process daemon
on the 8-device CPU mesh, once at ``workers=1`` (the driver shape) and
once at ``workers=8`` (one worker per device). The artifact records:

- ``histories_per_sec`` per run and the 8v1 ``ratio`` — the headline.
- Per-device occupancy (each slot's ``busy_s / wall``) from the
  placement stats block — proof the fleet actually spread.
- Stream batch occupancy — proof concurrent sessions shared vmapped
  carried-frontier programs (``stream_batch_max_occupancy > 1``).
- Full verdict parity against the CPU oracle in BOTH runs (zero
  flips; unknowns are honest failures and fail the gate).

The ratio gate scales to the machine: a fleet of N workers can beat
one worker only as far as real parallel capacity goes, so the target
is the ISSUE's 3x when ``min(workers, devices, cores) >= 4`` and a
no-regression bound otherwise (a 1-core sandbox cannot parallelize
compute; the honest number still lands in the artifact and the perf
ledger either way).

Chip-free: forces the CPU platform BEFORE jax backend init (CLAUDE.md)
— never takes the chip, safe to run next to a TPU process.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def _force_cpu_mesh() -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


# Eight distinct shape families -> eight scheduler bins in flight: the
# placement policy has real spreading to do (one fat bin would pin the
# whole workload to one home slot and measure nothing).
_FAMILIES = (
    ("cas-register", dict(n=100, concurrency=4, value_range=5)),
    ("cas-register", dict(n=200, concurrency=4, value_range=5)),
    ("cas-register", dict(n=60, concurrency=4, value_range=3)),
    ("cas-register", dict(n=100, concurrency=8, value_range=5)),
    ("cas-register", dict(n=400, concurrency=4, value_range=5)),
    ("mutex", dict(n=80, concurrency=4)),
    ("mutex", dict(n=160, concurrency=4)),
    ("register", dict(n=100, concurrency=4, value_range=5)),
)


def build_traffic(seed: int = 0, per_family: int = 6):
    """The seeded mixed workload: ``(check_jobs, stream_hists,
    txn_hists)``. Distinct seeds everywhere — fingerprint dedup must
    never quietly collapse the load."""
    from jepsen_tpu.lin import synth

    jobs: list[tuple[str, list]] = []
    for fi, (model_name, kw) in enumerate(_FAMILIES):
        for i in range(per_family):
            s = seed * 10000 + fi * 100 + i
            if model_name == "mutex":
                h = synth.generate_mutex_history(
                    kw["n"], concurrency=kw["concurrency"], seed=s)
            else:
                h = synth.generate_register_history(
                    kw["n"], concurrency=kw["concurrency"], seed=s,
                    value_range=kw["value_range"], crash_prob=0.01,
                    max_crashes=2)
            jobs.append((model_name, list(h)))
    streams = [list(synth.generate_register_history(
        240, concurrency=5, seed=seed * 777 + i, value_range=5))
        for i in range(4)]
    txns = [_txn_history(n=10 + 2 * i) for i in range(2)]
    return jobs, streams, txns


def _txn_history(n: int = 12) -> list:
    from jepsen_tpu.history import Op
    from jepsen_tpu.suites import fakes, workloads

    store = fakes.FakeTxnStore()
    client = workloads.TxnClient(store)
    h: list = []
    for i in range(n):
        op = Op("invoke", "txn",
                [["append", i % 3, i + 1], ["r", i % 3, None]], 0)
        h.append(op)
        h.append(client.invoke(None, op))
    return h


def oracles(jobs, streams, txns):
    from jepsen_tpu import models as m
    from jepsen_tpu import txn as txn_mod
    from jepsen_tpu.lin import cpu, prepare

    factories = {"cas-register": m.cas_register, "mutex": m.mutex,
                 "register": m.register}
    want_jobs = [cpu.check_packed(prepare.prepare(
        factories[name](), list(h)))["valid?"] for name, h in jobs]
    want_streams = [cpu.check_packed(prepare.prepare(
        m.cas_register(), list(h)))["valid?"] for h in streams]
    want_txns = [txn_mod.check(h, algorithm="cpu")["valid?"]
                 for h in txns]
    return want_jobs, want_streams, want_txns


def run_fleet(workers: int, jobs, streams, txns, *,
              clients: int = 6, flush_ms: float = 25.0,
              max_batch: int = 8) -> dict:
    """One timed pass of the mixed workload through an in-process
    daemon at ``workers``. A warm wave (one history per shape family,
    untimed) compiles each bin's programs on its HOME device first, so
    the timed wave measures the placed steady state."""
    from jepsen_tpu.service.daemon import CheckerService
    from jepsen_tpu.service.protocol import CheckerClient

    svc = CheckerService("127.0.0.1", 0, workers=workers,
                         flush_ms_=flush_ms,
                         max_batch_=max_batch).start()
    try:
        # Warm wave: first job of each family (untimed).
        warm = CheckerClient("127.0.0.1", svc.port, timeout=600)
        seen: set = set()
        for name, h in jobs:
            key = (name, len(h))
            if key in seen:
                continue
            seen.add(key)
            warm.submit(name, h)
        warm.close()

        lock = threading.Lock()
        results: dict[int, object] = {}
        stream_results: list = [None] * len(streams)
        txn_results: list = [None] * len(txns)
        errors: list = []
        it = iter(list(enumerate(jobs)))

        def check_loop():
            c = CheckerClient("127.0.0.1", svc.port, timeout=600)
            while True:
                with lock:
                    nxt = next(it, None)
                if nxt is None:
                    break
                i, (name, h) = nxt
                r = c.submit(name, h, req_id=i)
                with lock:
                    results[i] = r.get("valid?")
            c.close()

        def stream_loop(i):
            try:
                c = CheckerClient("127.0.0.1", svc.port, timeout=600)
                sid = c.stream_open("cas-register")
                h = streams[i]
                n = max(1, len(h) // 4)
                for j in range(0, len(h), n):
                    c.stream_append(sid, h[j:j + n])
                stream_results[i] = c.stream_finalize(sid)
                c.close()
            except Exception as e:  # noqa: BLE001 - audit, don't hang
                errors.append(f"stream[{i}]: {e!r}")

        def txn_loop():
            try:
                c = CheckerClient("127.0.0.1", svc.port, timeout=600)
                for i, h in enumerate(txns):
                    txn_results[i] = c.txn_check(h)
                c.close()
            except Exception as e:  # noqa: BLE001
                errors.append(f"txn: {e!r}")

        threads = [threading.Thread(target=check_loop)
                   for _ in range(clients)]
        threads += [threading.Thread(target=stream_loop, args=(i,))
                    for i in range(len(streams))]
        threads += [threading.Thread(target=txn_loop)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(1200)
        wall = time.monotonic() - t0
        sc = CheckerClient("127.0.0.1", svc.port)
        stats = sc.stats()
        sc.close()
    finally:
        svc.stop()

    block = stats.get("placement", {})
    occupancy = [
        {"slot": w.get("slot"), "device": w.get("device"),
         "items": w.get("items"),
         "busy_s": w.get("busy_s"),
         "occupancy": round((w.get("busy_s") or 0) / wall, 3),
         "compiles": w.get("compiles")}
        for w in block.get("workers", [])]
    return {
        "workers": workers,
        "wall_s": round(wall, 2),
        "checks": len(jobs),
        "histories_per_sec": round(len(jobs) / wall, 2),
        "check_verdicts": results,
        "stream_verdicts": [
            None if r is None else r.get("valid?")
            for r in stream_results],
        "stream_increments": [
            None if r is None else (r.get("stream") or {})
            .get("increments") for r in stream_results],
        "txn_verdicts": [None if r is None else r.get("valid?")
                         for r in txn_results],
        "errors": errors,
        "occupancy": occupancy,
        "placement": {k: block.get(k) for k in
                      ("placed", "homed", "spills", "re_homes")},
        "homes": len(block.get("homes") or {}),
        "stats": {k: stats.get(k) for k in
                  ("decided", "avg_occupancy", "stream_batches",
                   "stream_batched_increments",
                   "stream_batch_max_occupancy",
                   "stream_solo_increments", "dedup_hits",
                   "placement_spills", "xla_compiles")},
    }


def audit(run: dict, want_jobs, want_streams, want_txns) -> dict:
    """Zero-flip parity audit of one run against the CPU oracle.
    Unknown/missing answers are honest failures: they fail the gate
    but are reported as themselves, never as flips."""
    flips, unknowns, missing = [], 0, 0
    for i, w in enumerate(want_jobs):
        got = run["check_verdicts"].get(i)
        if got == w:
            continue
        if got == "unknown":
            unknowns += 1
        elif got is None:
            missing += 1
        else:
            flips.append({"kind": "check", "i": i, "want": w,
                          "got": got})
    for kind, got_list, want_list in (
            ("stream", run["stream_verdicts"], want_streams),
            ("txn", run["txn_verdicts"], want_txns)):
        for i, w in enumerate(want_list):
            got = got_list[i]
            if got == w:
                continue
            if got == "unknown":
                unknowns += 1
            elif got is None:
                missing += 1
            else:
                flips.append({"kind": kind, "i": i, "want": w,
                              "got": got})
    return {"flips": flips, "unknowns": unknowns, "missing": missing,
            "clean": not flips and not unknowns and not missing
            and not run["errors"]}


def main(argv=None) -> int:
    t_start = time.time()
    _force_cpu_mesh()
    import jax

    from jepsen_tpu import util

    util.enable_compile_cache()
    devices = len(jax.devices())
    cores = os.cpu_count() or 1

    jobs, streams, txns = build_traffic(seed=3)
    want_jobs, want_streams, want_txns = oracles(jobs, streams, txns)

    runs = {}
    audits = {}
    for workers in (1, 8):
        runs[workers] = run_fleet(workers, jobs, streams, txns)
        audits[workers] = audit(runs[workers], want_jobs,
                                want_streams, want_txns)

    ratio = (runs[8]["histories_per_sec"]
             / max(runs[1]["histories_per_sec"], 1e-9))
    capacity = min(8, devices, cores)
    # The ISSUE's 3x gate where the machine can parallelize at all;
    # a no-regression bound where it cannot (1-core CI sandbox).
    target = 3.0 if capacity >= 4 else 0.7
    stream_occ = runs[8]["stats"].get(
        "stream_batch_max_occupancy") or 0
    out = {
        "devices": devices, "cores": cores, "capacity": capacity,
        "runs": {str(k): {kk: vv for kk, vv in v.items()
                          if kk != "check_verdicts"}
                 for k, v in runs.items()},
        "parity": {str(k): a for k, a in audits.items()},
        "ratio_8v1": round(ratio, 2),
        "target_ratio": target,
        "stream_batch_max_occupancy": stream_occ,
        "ok": (audits[1]["clean"] and audits[8]["clean"]
               and ratio >= target and stream_occ > 1),
    }
    if capacity < 4:
        out["note"] = (f"parallel capacity {capacity} "
                       f"(cores={cores}): the 3x fleet target needs "
                       f">=4; gating no-regression instead — the "
                       f"honest ratio is recorded either way")

    from jepsen_tpu.obs import ledger as perf_ledger

    perf_ledger.record(
        "service-fleet-bench", kind="bench",
        wall_s=time.time() - t_start, verdict=out["ok"],
        extra={"ratio_8v1": out["ratio_8v1"],
               "hps_1": runs[1]["histories_per_sec"],
               "hps_8": runs[8]["histories_per_sec"],
               "stream_batch_max_occupancy": stream_occ,
               "capacity": capacity})
    print(json.dumps(out, default=str))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
