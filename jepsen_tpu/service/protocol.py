"""Checker-service wire protocol: framing, messages, client.

Framing is the simplest thing that every wire suite already speaks
(suites/common.py SocketIO): a 4-byte big-endian length prefix followed
by a JSON payload encoded with :mod:`jepsen_tpu.codec` (which carries
the non-JSON values Jepsen histories actually use — tuples, sets,
bytes). One message per frame, request/response with client-chosen ids
so responses may arrive out of submission order (the daemon decides
whole bins at once).

Messages (all dicts with a ``"type"`` key):

- ``{"type": "check", "id": I, "model": NAME, "history": [op dicts]}``
  → ``{"type": "verdict", "id": I, "result": {...}, "timings": {...}}``
  ``result`` is the checker verdict (``valid?`` / ``analyzer`` / ...);
  ``timings`` carries ``queue_wait_s`` / ``decide_s`` / ``batch_n``
  (how many histories shared the request's device program).
- ``{"type": "ping"}`` → ``{"type": "pong"}``
- ``{"type": "stats"}`` → ``{"type": "stats", "stats": {...}}``
- ``{"type": "shutdown"}`` → ``{"type": "ok"}`` then the daemon stops
  (the service is a trusted-network tool, like the results browser).

**Indeterminate semantics** (the wire suites' client contract,
suites/common.py): a connection lost after ``submit`` sent its frame is
INDETERMINATE — the daemon may have decided the history and the reply
was lost. The client completes such a submit with ``valid?
"unknown"``, never a definite verdict it did not receive, and never
retries the request in-place (the daemon would decide it twice;
harmless for a pure check but wrong for queue/occupancy accounting).
"""

from __future__ import annotations

import socket
import struct

import numpy as np

from jepsen_tpu import codec
from jepsen_tpu.history import Op
from jepsen_tpu.suites.common import (ReconnectExhausted, SocketIO,
                                      WireIndeterminate)

DEFAULT_PORT = 8642

# Registry of wire model names -> model factories: every shipped model
# family with a device or CPU checker formulation (models/kernels.py
# PACKED_STATE_KERNELS plus the history-sized set/queue kernels).
MODEL_NAMES = ("cas-register", "register", "mutex", "set",
               "unordered-queue", "fifo-queue")


def model_by_name(name: str):
    """Instantiate a fresh model from its wire name."""
    from jepsen_tpu import models as m

    factories = {"cas-register": m.cas_register, "register": m.register,
                 "mutex": m.mutex, "set": m.set_model,
                 "unordered-queue": m.unordered_queue,
                 "fifo-queue": m.fifo_queue}
    if name not in factories:
        raise ValueError(
            f"unknown model {name!r}; known: {', '.join(MODEL_NAMES)}")
    return factories[name]()


def jsonable(v):
    """Recursively convert a verdict/stats structure to codec-safe
    values: numpy scalars -> Python numbers, numpy arrays -> lists,
    anything else unserializable -> repr (verdicts carry LinOp-shaped
    dicts and host-stats; no consumer round-trips those as objects)."""
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return [jsonable(x) for x in v.tolist()]
    if isinstance(v, dict):
        return {(k if isinstance(k, str) else repr(k)): jsonable(x)
                for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [jsonable(x) for x in v]
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return repr(v)


def send_msg(io: SocketIO, msg: dict) -> None:
    payload = codec.encode(msg)
    io.send(struct.pack(">I", len(payload)) + payload)


def read_msg(io: SocketIO) -> dict:
    (n,) = struct.unpack(">I", io.read_exact(4))
    return codec.decode(io.read_exact(n))


def history_to_wire(history) -> list[dict]:
    return [op.to_dict() if isinstance(op, Op) else dict(op)
            for op in history]


def history_from_wire(ops: list[dict]) -> list[Op]:
    return [Op.from_dict(d) for d in ops]


class CheckerClient:
    """Synchronous client for the checker daemon.

    One in-flight request per client instance; concurrency = more
    clients (each holds one connection; the daemon interleaves bins
    across connections). ``submit`` returns the verdict dict, or an
    ``{"valid?": "unknown", "error": ...}`` indeterminate when the
    connection died after the request may have reached the daemon.
    """

    def __init__(self, host: str = "127.0.0.1",
                 port: int = DEFAULT_PORT, timeout: float = 600.0):
        self.io = SocketIO(connect=lambda: socket.create_connection(
            (host, port), timeout=timeout))
        self._next_id = 0

    def _rpc(self, msg: dict) -> dict:
        self.io.ensure_connected()
        send_msg(self.io, msg)
        return read_msg(self.io)

    def submit(self, model_name: str, history, req_id=None) -> dict:
        """Submit one history for checking; blocks for the verdict.
        Returns the result dict; ``_timings`` carries the daemon-side
        queue-wait/decide/batch-occupancy observability."""
        self._next_id += 1
        rid = req_id if req_id is not None else self._next_id
        try:
            resp = self._rpc({"type": "check", "id": rid,
                              "model": model_name,
                              "history": history_to_wire(history)})
            # One request in flight per client, but be defensive about
            # a stray frame (e.g. a daemon-side bug double-answering):
            # never attribute another request's verdict to this one.
            while resp.get("type") == "verdict" \
                    and resp.get("id") != rid:
                resp = read_msg(self.io)
        except WireIndeterminate as e:
            # The request may have reached (and been decided by) the
            # daemon; only the REPLY is known lost. Indeterminate.
            return {"valid?": "unknown",
                    "error": f"indeterminate: {e}"}
        if resp.get("type") == "error":
            return {"valid?": "unknown",
                    "error": resp.get("error", "daemon error")}
        out = dict(resp.get("result") or {})
        if resp.get("timings"):
            out["_timings"] = resp["timings"]
        return out

    def ping(self) -> bool:
        try:
            return self._rpc({"type": "ping"}).get("type") == "pong"
        except (WireIndeterminate, ReconnectExhausted, OSError):
            return False

    def stats(self) -> dict:
        return self._rpc({"type": "stats"}).get("stats", {})

    def shutdown(self) -> None:
        try:
            self._rpc({"type": "shutdown"})
        except (WireIndeterminate, ReconnectExhausted, OSError):
            pass  # the daemon may close before/while acking

    def close(self) -> None:
        self.io.close()
