"""Checker-service wire protocol: framing, messages, client.

Framing is the simplest thing that every wire suite already speaks
(suites/common.py SocketIO): a 4-byte big-endian length prefix followed
by a JSON payload encoded with :mod:`jepsen_tpu.codec` (which carries
the non-JSON values Jepsen histories actually use — tuples, sets,
bytes). One message per frame, request/response with client-chosen ids
so responses may arrive out of submission order (the daemon decides
whole bins at once).

Messages (all dicts with a ``"type"`` key):

- ``{"type": "check", "id": I, "model": NAME, "history": [op dicts]}``
  → ``{"type": "verdict", "id": I, "result": {...}, "timings": {...}}``
  ``result`` is the checker verdict (``valid?`` / ``analyzer`` / ...);
  ``timings`` carries ``queue_wait_s`` / ``decide_s`` / ``batch_n``
  (how many histories shared the request's device program).
- ``{"type": "txn-check", "id": I, "history": [op dicts],
  "anomalies": [...] | None, "consistency": NAME, "realtime":
  bool | None, "algorithm": "tpu" | "cpu"}`` → a ``verdict`` frame.
  The txn twin of ``check`` (v2): a list-append transaction history
  decided by ``checker.txn_cycles`` under the daemon's supervised
  per-request fallthrough (txn requests never bin).
- ``{"type": "result-fetch", "id": I, "fp": FINGERPRINT}`` → a
  ``verdict`` frame with ``"fetched": true`` when the journal holds a
  SETTLED record for that request fingerprint, else a structured
  ``error`` with ``"status": "pending" | "unknown"`` — the journal-
  aware reconnect path: a client whose submit completed indeterminate
  recomputes its fingerprint (:func:`request_fingerprint`) and reads
  the durably settled verdict back after reconnecting. The fetch
  returns the settled record or an honest not-found, NEVER a guess
  (the ``:info`` contract, doc/service.md § Failure semantics).
- ``{"type": "ping"}`` → ``{"type": "pong"}``
- ``{"type": "stats"}`` → ``{"type": "stats", "stats": {...}}``
- ``{"type": "shutdown"}`` → ``{"type": "ok"}`` then the daemon stops
  (the service is a trusted-network tool, like the results browser).

**Stream-check family (v2, doc/streaming.md)** — a daemon-side
:class:`jepsen_tpu.stream.StreamChecker` session holds the carried
frontier between appends, so another process can stream a run at the
daemon's warm chip:

- ``{"type": "stream-open", "id": I, "model": NAME}``
  → ``{"type": "stream-opened", "id": I, "session": SID}`` (or an
  ``error`` when the session-slot bound is reached — backpressure,
  like ``overload``). With ``"session": SID`` the open RE-ADOPTS a
  journaled session a crash or client drop orphaned (doc/service.md
  § Fleet): the daemon re-feeds the journaled appends (fast-forwarded
  by the session's per-sid ``JEPSEN_TPU_STREAM_CKPT`` checkpoint) and
  answers ``stream-opened`` with ``"resumed": true`` plus the current
  session state.
- ``{"type": "stream-append", "session": SID, "ops": [op dicts]}``
  → ``{"type": "stream-state", "session": SID, "row": R, ...}``; once
  an increment proves the history invalid the state carries
  ``"aborted": true`` and the witness under ``"result"`` — the client
  should stop producing.
- ``{"type": "stream-finalize", "session": SID}`` → a ``verdict``
  frame with the full-history result; the session slot is freed.
- ``{"type": "stream-abort", "session": SID}`` → ``{"type": "ok"}``;
  slot freed, no verdict.

A client that disconnects mid-session is REAPED: the daemon drops its
sessions and frees their slots (sessions are connection-owned).

**Protocol version.** Every frame carries ``"v": PROTOCOL_VERSION``
(stamped by :func:`send_msg`); the daemon checks it on every request
and answers a structured ``error`` naming both versions on a mismatch
— the stream frames are the first wire change since PR 6, and an old
client against a new daemon should learn that in one readable frame,
not via an opaque codec failure.

**Indeterminate semantics** (the wire suites' client contract,
suites/common.py): a connection lost after ``submit`` sent its frame is
INDETERMINATE — the daemon may have decided the history and the reply
was lost. The client completes such a submit with ``valid?
"unknown"``, never a definite verdict it did not receive, and never
retries the request in-place (the daemon would decide it twice;
harmless for a pure check but wrong for queue/occupancy accounting).
"""

from __future__ import annotations

import socket
import struct

import numpy as np

from jepsen_tpu import codec
from jepsen_tpu.history import Op
from jepsen_tpu.suites.common import (ReconnectExhausted, SocketIO,
                                      WireIndeterminate)

DEFAULT_PORT = 8642

# Wire protocol version: bumped to 2 when the stream-check family (and
# this very field) landed. v1 frames carried no version; the daemon
# treats an absent field as v1 and answers a structured mismatch error.
PROTOCOL_VERSION = 2

# Registry of wire model names -> model factories: every shipped model
# family with a device or CPU checker formulation (models/kernels.py
# PACKED_STATE_KERNELS plus the history-sized set/queue kernels).
MODEL_NAMES = ("cas-register", "register", "mutex", "set",
               "unordered-queue", "fifo-queue")


def model_by_name(name: str):
    """Instantiate a fresh model from its wire name."""
    from jepsen_tpu import models as m

    factories = {"cas-register": m.cas_register, "register": m.register,
                 "mutex": m.mutex, "set": m.set_model,
                 "unordered-queue": m.unordered_queue,
                 "fifo-queue": m.fifo_queue}
    if name not in factories:
        raise ValueError(
            f"unknown model {name!r}; known: {', '.join(MODEL_NAMES)}")
    return factories[name]()


def jsonable(v):
    """Recursively convert a verdict/stats structure to codec-safe
    values: numpy scalars -> Python numbers, numpy arrays -> lists,
    anything else unserializable -> repr (verdicts carry LinOp-shaped
    dicts and host-stats; no consumer round-trips those as objects)."""
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return [jsonable(x) for x in v.tolist()]
    if isinstance(v, dict):
        return {(k if isinstance(k, str) else repr(k)): jsonable(x)
                for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [jsonable(x) for x in v]
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return repr(v)


def send_msg(io: SocketIO, msg: dict) -> None:
    if "v" not in msg:
        msg = {**msg, "v": PROTOCOL_VERSION}
    payload = codec.encode(msg)
    io.send(struct.pack(">I", len(payload)) + payload)


def read_msg(io: SocketIO) -> dict:
    (n,) = struct.unpack(">I", io.read_exact(4))
    return codec.decode(io.read_exact(n))


def request_fingerprint(model_name: str, history) -> str | None:
    """The daemon's fingerprint for a check request, computed
    CLIENT-side — the key ``result-fetch`` looks up. Must match the
    admission path bit for bit: ``pack_dev.prepack`` then
    ``pack_dev.prepack_fingerprint`` over the PRE-pack columns (the
    grids never exist on this path — the client pays the cheap pack
    half only, the mode-invariance the device-packer tests pin).
    Returns None for an unpackable history (the daemon fingerprints
    those randomly per-request, so their settles are honestly
    unfetchable)."""
    from jepsen_tpu.lin import pack_dev, prepare

    try:
        pre = pack_dev.prepack(model_by_name(model_name), history)
    except prepare.UnsupportedHistory:
        return None
    return pack_dev.prepack_fingerprint(pre)


def history_to_wire(history) -> list[dict]:
    return [op.to_dict() if isinstance(op, Op) else dict(op)
            for op in history]


def history_from_wire(ops: list[dict]) -> list[Op]:
    return [Op.from_dict(d) for d in ops]


class CheckerClient:
    """Synchronous client for the checker daemon.

    One in-flight request per client instance; concurrency = more
    clients (each holds one connection; the daemon interleaves bins
    across connections). ``submit`` returns the verdict dict, or an
    ``{"valid?": "unknown", "error": ...}`` indeterminate when the
    connection died after the request may have reached the daemon.
    """

    def __init__(self, host: str = "127.0.0.1",
                 port: int = DEFAULT_PORT, timeout: float = 600.0):
        self.io = SocketIO(connect=lambda: socket.create_connection(
            (host, port), timeout=timeout))
        self._next_id = 0

    def _rpc(self, msg: dict) -> dict:
        self.io.ensure_connected()
        send_msg(self.io, msg)
        return read_msg(self.io)

    def submit(self, model_name: str, history, req_id=None) -> dict:
        """Submit one history for checking; blocks for the verdict.
        Returns the result dict; ``_timings`` carries the daemon-side
        queue-wait/decide/batch-occupancy observability."""
        self._next_id += 1
        rid = req_id if req_id is not None else self._next_id
        try:
            resp = self._rpc({"type": "check", "id": rid,
                              "model": model_name,
                              "history": history_to_wire(history)})
            # One request in flight per client, but be defensive about
            # a stray frame (e.g. a daemon-side bug double-answering):
            # never attribute another request's verdict to this one.
            while resp.get("type") == "verdict" \
                    and resp.get("id") != rid:
                resp = read_msg(self.io)
        except WireIndeterminate as e:
            # The request may have reached (and been decided by) the
            # daemon; only the REPLY is known lost. Indeterminate.
            return {"valid?": "unknown",
                    "error": f"indeterminate: {e}"}
        if resp.get("type") == "error":
            return {"valid?": "unknown",
                    "error": resp.get("error", "daemon error")}
        out = dict(resp.get("result") or {})
        if resp.get("timings"):
            out["_timings"] = resp["timings"]
        return out

    def txn_check(self, history, *, anomalies=None,
                  consistency: str = "serializable",
                  realtime: bool | None = None,
                  algorithm: str = "tpu", req_id=None) -> dict:
        """Submit a list-append TRANSACTION history (the txn-check
        frame, v2); blocks for the verdict with the same indeterminate
        semantics as ``submit``."""
        self._next_id += 1
        rid = req_id if req_id is not None else self._next_id
        try:
            resp = self._rpc({"type": "txn-check", "id": rid,
                              "history": history_to_wire(history),
                              "anomalies": (list(anomalies)
                                            if anomalies else None),
                              "consistency": consistency,
                              "realtime": realtime,
                              "algorithm": algorithm})
            while resp.get("type") == "verdict" \
                    and resp.get("id") != rid:
                resp = read_msg(self.io)
        except WireIndeterminate as e:
            return {"valid?": "unknown",
                    "error": f"indeterminate: {e}"}
        if resp.get("type") == "error":
            return {"valid?": "unknown",
                    "error": resp.get("error", "daemon error")}
        out = dict(resp.get("result") or {})
        if resp.get("timings"):
            out["_timings"] = resp["timings"]
        return out

    def result_fetch(self, model_name: str | None = None,
                     history=None, *, fp: str | None = None,
                     req_id=None) -> dict:
        """Read a SETTLED verdict back from the daemon's journal by
        request fingerprint — the reconnect path for a submit that
        completed indeterminate (the check may have been decided and
        the reply lost). Pass the original ``model_name``/``history``
        (the fingerprint is recomputed exactly as admission computed
        it) or an explicit ``fp``. Returns the settled result dict, or
        an honest ``{"valid?": "unknown", "fetch_status": "pending" |
        "unknown" | ...}`` — never a guess."""
        if fp is None:
            if model_name is None or history is None:
                raise ValueError(
                    "result_fetch needs (model_name, history) or fp=")
            fp = request_fingerprint(model_name, history)
            if fp is None:
                return {"valid?": "unknown",
                        "fetch_status": "unfetchable",
                        "error": "unpackable history: the daemon "
                                 "fingerprints these per-request, so "
                                 "their settles cannot be fetched"}
        self._next_id += 1
        rid = req_id if req_id is not None else self._next_id
        try:
            resp = self._rpc({"type": "result-fetch", "id": rid,
                              "fp": fp})
            while resp.get("type") == "verdict" \
                    and resp.get("id") != rid:
                resp = read_msg(self.io)
        except WireIndeterminate as e:
            return {"valid?": "unknown", "fetch_status": "wire",
                    "error": f"indeterminate: {e}"}
        if resp.get("type") == "error":
            return {"valid?": "unknown",
                    "fetch_status": resp.get("status", "unknown"),
                    "error": resp.get("error", "daemon error")}
        out = dict(resp.get("result") or {})
        out["fetched"] = True
        return out

    # --- stream-check sessions (doc/streaming.md) -----------------------

    def stream_open(self, model_name: str,
                    session: str | None = None):
        """Open a daemon-side streaming session; returns its id.
        With ``session``, RE-ADOPT that journaled session (a crashed
        or dropped producer resuming its stream) — returns the full
        ``stream-opened`` reply dict, which carries ``resumed`` /
        ``replayed_appends`` and the current session state. Raises
        RuntimeError on refusal (bound reached, unknown session,
        version skew)."""
        self._next_id += 1
        msg = {"type": "stream-open", "id": self._next_id,
               "model": model_name}
        if session is not None:
            msg["session"] = session
        resp = self._rpc(msg)
        if resp.get("type") != "stream-opened":
            raise RuntimeError(
                f"stream-open refused: {resp.get('error', resp)!r}")
        return dict(resp) if session is not None else resp["session"]

    def stream_append(self, session: str, ops) -> dict:
        """Append history events to a stream session; returns the
        session state (``aborted``/``result`` once an increment proved
        the history invalid). A lost connection is INDETERMINATE, like
        ``submit``: the append may have been ingested."""
        try:
            resp = self._rpc({"type": "stream-append",
                              "session": session,
                              "ops": history_to_wire(ops)})
        except WireIndeterminate as e:
            return {"valid?": "unknown", "error": f"indeterminate: {e}"}
        if resp.get("type") == "error":
            return {"valid?": "unknown",
                    "error": resp.get("error", "daemon error")}
        return dict(resp)

    def stream_finalize(self, session: str) -> dict:
        """Finalize a stream session; returns the full-history verdict
        (the session slot is freed either way)."""
        try:
            resp = self._rpc({"type": "stream-finalize",
                              "session": session})
        except WireIndeterminate as e:
            return {"valid?": "unknown", "error": f"indeterminate: {e}"}
        if resp.get("type") == "error":
            return {"valid?": "unknown",
                    "error": resp.get("error", "daemon error")}
        return dict(resp.get("result") or {})

    def stream_abort(self, session: str) -> None:
        try:
            self._rpc({"type": "stream-abort", "session": session})
        except (WireIndeterminate, ReconnectExhausted, OSError):
            pass   # the daemon reaps dropped sessions anyway

    def ping(self) -> bool:
        try:
            return self._rpc({"type": "ping"}).get("type") == "pong"
        except (WireIndeterminate, ReconnectExhausted, OSError):
            return False

    def stats(self) -> dict:
        return self._rpc({"type": "stats"}).get("stats", {})

    def shutdown(self) -> None:
        try:
            self._rpc({"type": "shutdown"})
        except (WireIndeterminate, ReconnectExhausted, OSError):
            pass  # the daemon may close before/while acking

    def close(self) -> None:
        self.io.close()
