"""Checker-as-a-service: a persistent shape-binned batch daemon.

The survey's north star is the chip deciding histories as fast as the
hardware allows — but one process per history pays the 15-70 s XLA
compiles and the ~100 ms tunnel dispatch per run. Production scale is
the opposite shape: thousands of SMALL queued histories from many
concurrent test runs. This package amortizes the warm chip across them:

- :mod:`jepsen_tpu.service.protocol` — length-prefixed JSON framing
  over :class:`jepsen_tpu.suites.common.SocketIO` (the same framing
  loop every wire suite uses), plus :class:`CheckerClient` with the
  suites' indeterminate semantics: a connection lost after a request
  may have reached the daemon completes ``valid? "unknown"`` — never a
  definite verdict that wasn't computed.
- :mod:`jepsen_tpu.service.daemon` — :class:`CheckerService`: bounded
  admission queue with backpressure, a scheduler that fingerprints and
  bins requests by traced shape (window bucket, state/NS, model
  kernel, engine route — the :mod:`jepsen_tpu.lin.supervise` shape-key
  codec), continuous batching (max-batch / max-wait flush), and a warm
  single-chip worker: same-shape bins decide as ONE vmapped
  :mod:`jepsen_tpu.lin.batched` program; odd shapes fall through to
  ``lin.device_check_packed`` under the supervision ladder with a
  per-request deadline. A worker fault costs the in-flight bin (one
  requeue, then an honest failure), never the daemon.
- :mod:`jepsen_tpu.service.journal` — the durable request journal
  (``JEPSEN_TPU_SERVICE_JOURNAL``): every admitted check / txn-check /
  stream event is JSONL-journaled before it is decided, answers append
  settle records, and a restarted daemon replays the unsettled tail —
  a daemon crash costs in-flight LATENCY, never in-flight work.
- :mod:`jepsen_tpu.service.chaos` — the checker-side chaos nemesis:
  drives a daemon through seeded wedge/fault/worker-kill/restart
  schedules under concurrent clients and asserts the soundness
  invariant (verdicts match the CPU oracle or degrade to honest
  ``unknown`` — never flip, never duplicate, never vanish).
  ``make fleet-smoke`` is its SIGKILL-restart proof.
- :mod:`jepsen_tpu.service.smoke` — the ``make serve-smoke`` start →
  submit → assert → shutdown proof on the forced-CPU mesh.

Entry points: ``python -m jepsen_tpu.cli serve-checker`` and
``cli.py service-stats`` / ``cli.py journal``; all
``JEPSEN_TPU_SERVICE_*`` knobs are tabled in ``doc/env.md``; protocol,
capacity planning, and the fleet semantics in ``doc/service.md``.
"""

from jepsen_tpu.service.journal import Journal  # noqa: F401
from jepsen_tpu.service.protocol import CheckerClient  # noqa: F401
