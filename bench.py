"""Headline benchmark: linearizability checking throughput on device.

North star (BASELINE.md): decide a 100k-op CAS-register history in <60 s
where CPU knossos DNFs. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
with vs_baseline = achieved ops/s over the 100k-in-60s target rate.

The history carries crashed (:info) ops — the frontier-inflating case that
makes list-based checkers struggle — checked by the dense config-space
bitmap engine (jepsen_tpu.lin.dense), which crashed ops cost nothing
extra. Runs on whatever jax.devices() provides (the real TPU chip under
the driver).

Hardened: any failure on the crashed-op history still reports the
crash-free number with an "error" field instead of a bare nonzero exit,
so a round never records zero information.
"""

from __future__ import annotations

import json
import sys
import time
import traceback

N_OPS = 100_000
TARGET_SECONDS = 60.0


def _check_timed(history, n_ops):
    """(prepare_s, warm check to compile every chunk bucket, timed check).
    Returns (ops_per_sec, detail_dict); raises on any failure."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import device_check_packed, prepare

    t0 = time.time()
    p = prepare.prepare(m.cas_register(), history)
    prep_s = time.time() - t0

    # Big chunks amortize the per-dispatch fixed costs (the bench wants
    # peak sustained throughput; the default is tuned for verdict+witness
    # latency instead). Measured on the v5e chip: 106k ops/s at 32768,
    # 118k at 65536.
    kw = {"chunk": 65536}

    # Warm run: compiles every (window-bucket, state-bucket) program this
    # history touches, so the timed runs measure steady-state throughput.
    r = device_check_packed(p, **kw)
    if r["valid?"] is not True:
        raise RuntimeError(f"unexpected verdict {r}")

    # Best of three: the shared-chip tunnel occasionally stalls a run.
    check_s = float("inf")
    for _ in range(3):
        t0 = time.time()
        r = device_check_packed(p, **kw)
        check_s = min(check_s, time.time() - t0)
        if r["valid?"] is not True:
            raise RuntimeError(f"unexpected verdict {r}")

    return n_ops / check_s, {
        "n_ops": n_ops, "check_seconds": round(check_s, 3),
        "prepare_seconds": round(prep_s, 2),
        "window": p.window, "return_events": int(p.R),
        "verdict": r["valid?"], "analyzer": r.get("analyzer")}


def _wide_window_probe(detail: dict) -> None:
    """Secondary capability probe: a window-26 concurrency-30 register
    history — the class where list-based searches (and the reference's
    knossos, per BASELINE config 5's concurrency, cockroach.clj:40-41)
    DNF outright. Decided by the sparse engine's exact reductions + the
    spike executor. Never fails the bench; records timing or the error.
    Skippable via JEPSEN_TPU_BENCH_WIDE=0."""
    import os
    import time
    import traceback

    if os.environ.get("JEPSEN_TPU_BENCH_WIDE", "1") == "0":
        return
    try:
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import device_check_packed, prepare, synth

        h = synth.generate_register_history(
            500, concurrency=30, seed=7, value_range=5,
            crash_prob=0.002, max_crashes=4)
        p = prepare.prepare(m.cas_register(), h)
        t0 = time.time()
        r = device_check_packed(p)
        detail["wide_window_c30"] = {
            "n_ops": 500, "window": p.window,
            "verdict": r.get("valid?"),
            "analyzer": r.get("analyzer"),
            "seconds": round(time.time() - t0, 1)}
    except Exception:
        detail["wide_window_c30"] = {
            "error": traceback.format_exc(limit=2)}


def main() -> None:
    from jepsen_tpu.lin import synth
    from jepsen_tpu.util import enable_compile_cache

    enable_compile_cache()

    target_rate = N_OPS / TARGET_SECONDS
    out = {"metric": "lin_check_ops_per_sec", "value": 0,
           "unit": "ops/s", "vs_baseline": 0}

    try:
        h = synth.generate_register_history(
            N_OPS, concurrency=5, seed=42, value_range=5,
            crash_prob=0.001, max_crashes=10)
        rate, detail = _check_timed(h, N_OPS)
        out.update(value=round(rate, 1),
                   vs_baseline=round(rate / target_rate, 3),
                   detail=detail)
        _wide_window_probe(detail)
    except Exception:
        err = traceback.format_exc(limit=3)
        # Partial signal: the crash-free 100k history on the same engine.
        try:
            h = synth.generate_register_history(
                N_OPS, concurrency=5, seed=42, value_range=5, crash_prob=0)
            rate, detail = _check_timed(h, N_OPS)
            detail["variant"] = "crash-free fallback"
            out.update(value=round(rate, 1),
                       vs_baseline=round(rate / target_rate, 3),
                       detail=detail,
                       error=f"crashed-op run failed: {err}")
        except Exception:
            out.update(error=f"crashed-op run failed: {err}; "
                             f"fallback failed: "
                             f"{traceback.format_exc(limit=3)}")

    print(json.dumps(out))
    sys.stdout.flush()
    sys.exit(0 if "error" not in out else (0 if out["value"] else 1))


if __name__ == "__main__":
    main()
