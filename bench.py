"""Headline benchmark: linearizability checking throughput on device.

North star (BASELINE.md): decide a 100k-op CAS-register history in <60 s
where CPU knossos DNFs. Prints JSON lines of the shape:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
with vs_baseline = achieved ops/s over the 100k-in-60s target rate.

LOSS-PROOF ARTIFACT CONTRACT: the driver parses the LAST stdout JSON
line. The headline line is printed (and flushed) the moment
``_check_timed`` returns, and the full line is RE-printed after every
probe completes — each intermediate line is a strictly better partial
result, so an external timeout at ANY point leaves the best numbers so
far on stdout (round 5 recorded nothing: BENCH_r05.json is rc=124,
parsed=null, because the only print sat after a 5300 s probe budget).
The ``partitioned_c30`` budget is derived from the wall time already
spent, so the worst-case total stays inside the driver's budget — with
one exception: the probe never gets less than PARTITIONED_MIN_S (the
headline probe is worth starting even with the clock nearly spent,
because every earlier number is ALREADY emitted, so an external kill
mid-probe costs only the partitioned result itself).

The headline history carries crashed (:info) ops — the frontier-inflating
case that makes list-based checkers struggle — checked by the dense
config-space bitmap engine (jepsen_tpu.lin.dense), which crashed ops cost
nothing extra. Secondary probes cover BASELINE configs 3-5:

- ``pack``: chip-free host-pack micro-rung — the 100k-op config-5
  history packed under both packer modes (vectorized vs Python spec
  walk), bit-parity asserted, speedup recorded to the perf ledger.
  Runs FIRST: it needs no chip and its ledger record is the standing
  pack-wall evidence.
- ``mutex_c30``: lock histories at concurrency 30 (config 3).
- ``wide_window_c30``: a saturated single-register history at
  concurrency 30 (window ~26) — the class knossos DNFs on.
- ``independent_keys``: 1k keys' subhistories decided in one vmapped
  device batch (config 4, independent.clj:246-296).
- ``txn_c30``: 100k-op list-append transactional history through the
  txn dependency-graph checker (jepsen_tpu.txn) — healthy leg plus a
  spliced-anomaly leg with oracle parity (edges/s, anomaly counts).
- ``fused_pair``: the raised-bound PAIR-KEY fused fixpoint tier
  (JEPSEN_TPU_PSORT_FUSED_MAX_N) on the crash-free saturated pair
  band — the only band where it can engage (crash-dom histories keep
  the forced-lax chain rule) — small-input smoke first, then an
  unfused/default/raised A/B/A with verdict parity. Fault-isolated
  and ordered before partitioned_c30 so a Mosaic fault in the
  never-probed shape cannot cost the headline.
- ``partitioned_c30``: the literal config-5 shape — a 100k-op
  partition-nemesis history, 24 crashed mutators, window 49.

FAULT ISOLATION: every secondary probe runs in its own subprocess
(``python bench.py --probe KEY``), so a TPU worker crash kills the
child, not the bench — round 4 lost a known-good probe to the previous
probe's kernel fault. Probes run safe-first; after a failed probe the
bench waits out the ~60 s worker restart and verifies recovery with a
trivial dispatch before the next probe.

Runs on whatever jax.devices() provides (the real TPU chip under the
driver).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import traceback

N_OPS = 100_000
TARGET_SECONDS = 60.0

# (key, timeout_seconds) safe-first: the long/dangerous partitioned
# probe runs LAST so it cannot shadow any other number. Its listed
# budget is a CEILING — the actual budget is derived from the wall
# time already spent (_partitioned_budget), so the bench total stays
# inside the driver's budget instead of losing the artifact to an
# external timeout (BENCH_r05: rc=124, parsed=null).
PROBE_ORDER = (("pack", 300), ("mutex_c30", 600),
               ("wide_window_c30", 600),
               ("independent_keys", 900), ("service_c30", 900),
               ("txn_c30", 900), ("stream_c30", 900),
               ("fused_pair", 900), ("mesh_c30", 900),
               ("partitioned_c30", 5300))
WORKER_RESTART_S = 75
# Overall bench wall budget the partitioned probe must fit inside
# (env-overridable for driver environments with different budgets).
TOTAL_BUDGET_S = float(os.environ.get("JEPSEN_TPU_BENCH_BUDGET", 7000))
PARTITIONED_MIN_S = 900
# Budget for the SMALL-input probe of the K-row wave program that
# gates the wave rungs of the partitioned ladder (CLAUDE.md: probe new
# kernels on small inputs with a timeout first — a fault kills the
# worker for ~a minute, and a wedge would otherwise burn a full
# partitioned stall window before the ladder fell through).
WAVE_SMOKE_BUDGET_S = 600

# Probe stall watchdog: children emit "HB <progress>" heartbeat lines
# every HEARTBEAT_S from the engines' liveness counter
# (jepsen_tpu.util.progress). The parent treats a probe as WEDGED —
# kill + one retry, recorded in the artifact — when the counter stops
# advancing for the stall window (and no other output arrives): the
# shared-chip tunnel has stalled single dispatches ~25 min, and a
# wedged probe should cost its detection window, not its whole budget.
# The partitioned probe gets a WIDER window: the fused host-row
# closure freezes the counter for one whole (row, capacity) fixpoint
# program — up to it_max passes of cap-524288 dedups in ONE dispatch —
# which can legitimately run many minutes where every other probe's
# longest dispatch is seconds. Both are env-overridable so a driver
# with different tunnel behaviour can retune without a code change.
HEARTBEAT_S = 20
STALL_S = float(os.environ.get("JEPSEN_TPU_BENCH_STALL_S", 600))
PARTITIONED_STALL_S = float(
    os.environ.get("JEPSEN_TPU_BENCH_STALL_PART_S", 1800))
# Grace between SIGTERM and SIGKILL on a wedged child: SIGTERM lets a
# child that is merely slow flush its result line; a child wedged
# inside the TPU runtime ignores it and needs SIGKILL (a wedged
# teardown used to leave the child alive and the kill unrecorded).
KILL_GRACE_S = float(os.environ.get("JEPSEN_TPU_BENCH_KILL_GRACE_S", 10))


def _emit(out: dict) -> None:
    """Print the full result line NOW (the driver parses the last
    stdout JSON line; every emission strictly improves on the one
    before it, so emitting early and often is what makes the artifact
    survive external timeouts)."""
    print(json.dumps(out))
    sys.stdout.flush()


def _partitioned_budget(t_start: float, ceiling: int) -> int:
    """partitioned_c30's budget = what's left of the bench's total wall
    budget, clamped to [PARTITIONED_MIN_S, ceiling]. The floor can push
    the bench past TOTAL_BUDGET_S when earlier probes ran long — that
    is deliberate (see module docstring): all earlier numbers are
    already emitted, so overrunning risks only this probe's own
    result."""
    remaining = TOTAL_BUDGET_S - (time.time() - t_start)
    return int(max(PARTITIONED_MIN_S, min(ceiling, remaining)))


def _check_timed(history, n_ops):
    """(prepare_s, warm check to compile every chunk bucket, timed check).
    Returns (ops_per_sec, detail_dict); raises on any failure."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import device_check_packed, prepare

    t0 = time.time()
    p = prepare.prepare(m.cas_register(), history)
    prep_s = time.time() - t0

    # Big chunks amortize the per-dispatch fixed costs (the bench wants
    # peak sustained throughput; the default is tuned for verdict+witness
    # latency instead). Measured on the v5e chip: 106k ops/s at 32768,
    # 118k at 65536.
    kw = {"chunk": 65536}

    # Warm run: compiles every (window-bucket, state-bucket) program this
    # history touches, so the timed runs measure steady-state throughput.
    r = device_check_packed(p, **kw)
    if r["valid?"] is not True:
        raise RuntimeError(f"unexpected verdict {r}")

    # Best of three: the shared-chip tunnel occasionally stalls a run.
    # ALL three run times are recorded (check_seconds_runs) so tunnel
    # variance is visible in the artifact — BENCH_r03's 87.7k "regression"
    # against r02's 120k was chip contention, not code (re-measured
    # 0.824 s on the same commit).
    runs = []
    for _ in range(3):
        t0 = time.time()
        r = device_check_packed(p, **kw)
        runs.append(round(time.time() - t0, 3))
        if r["valid?"] is not True:
            raise RuntimeError(f"unexpected verdict {r}")
    check_s = min(runs)

    return n_ops / check_s, {
        "n_ops": n_ops, "check_seconds": round(check_s, 3),
        "check_seconds_runs": runs,
        "prepare_seconds": round(prep_s, 2),
        # Honest end-to-end rate: host packing + device check. The
        # device-only number is the headline (prepare is amortizable:
        # it's one linear pass, reusable across re-checks), but both
        # are recorded so no claim needs the favorable denominator.
        "end_to_end_ops_per_sec": round(n_ops / (check_s + prep_s), 1),
        "window": p.window, "return_events": int(p.R),
        "verdict": r["valid?"], "analyzer": r.get("analyzer"),
        # Which dense chunk backend decided (VERDICT r4 #4): "pallas"
        # is the in-VMEM whole-frontier kernel, auto-routed on TPU
        # since round 4 (dense.py backend="auto").
        "dense_backend": r.get("backend")}


def _timed_check(make_history, n_ops, model=None, warm=True):
    """Warm once (compile), then time one device check. ``warm=False``
    times the first run instead (long probes: the persistent compile
    cache already amortizes compiles, and a second multi-minute run
    would blow the probe budget for no extra information). Returns the
    probe's result dict."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import device_check_packed, prepare

    h = make_history()
    prepare.reset_pack_stats()
    p = prepare.prepare(model if model is not None
                        else m.cas_register(), h)
    pack = prepare.pack_stats()
    if warm:
        r = device_check_packed(p)      # warm/compile
    t0 = time.time()
    r = device_check_packed(p)
    dt = time.time() - t0
    out = {
        "n_ops": n_ops, "window": p.window,
        "crashed": len(p.crashed_ops),
        "verdict": r.get("valid?"),
        "analyzer": r.get("analyzer"),
        "timed_run": "steady" if warm else "first",
        "seconds": round(dt, 1),
        "ops_per_sec": round(n_ops / dt, 1),
        # Host pack cost + packer mode (ISSUE 16): rides into the
        # perf-ledger record via _probe_main so `perf report` trends
        # the pack wall next to the check wall for every probe.
        "pack": {"prepare_s": round(pack["prepare_s"], 3),
                 "mode": pack["mode"]}}
    # Engine observability: the host-row executor's episode/dispatch/
    # pass counters (the tunnel round trips the fused closure fixpoint
    # is cutting — the round-6 acceptance metric) and the top capacity.
    if r.get("host-stats") is not None:
        out["host_stats"] = r["host-stats"]
    if r.get("max-cap") is not None:
        out["max_cap"] = r["max-cap"]
    if r.get("resumed-from-row") is not None:
        # The run continued a checkpoint (JEPSEN_TPU_CKPT) instead of
        # restarting from op 0 — the timing covers only the resumed
        # tail, so the artifact must say so.
        out["resumed_from_row"] = r["resumed-from-row"]
    return out


def _probe_ping():
    """Trivial device dispatch: proves the TPU worker is back up."""
    import jax
    import jax.numpy as jnp

    x = int(jnp.sum(jnp.arange(8)))
    return {"ok": x == 28, "platform": jax.devices()[0].platform}


def _probe_mutex_c30():
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import synth

    return _timed_check(
        lambda: synth.generate_mutex_history(
            5000, concurrency=30, seed=7, crash_prob=0.002,
            max_crashes=4), 5000, model=m.mutex())


def _probe_wide_window_c30():
    from jepsen_tpu.lin import synth

    r = _timed_check(
        lambda: synth.generate_register_history(
            500, concurrency=30, seed=7, value_range=5,
            crash_prob=0.002, max_crashes=4), 500)
    r["note"] = ("adversarial ceiling: fully saturated window-26 "
                 "schedule, denser than the config-5 pacing "
                 "partitioned_c30 measures")
    return r


def _probe_partitioned_c30():
    # The literal config-5 shape at the reference's staggered pacing
    # (etcd.clj:167-179 staggers invocations; invoke_bias=0.45 models
    # that): 30 processes, partition crashes, ~6-13 live ops in flight,
    # 24 crashed mutators accumulating over ~50 partition cycles
    # (window 49) — at the LITERAL 100k-op size of BASELINE config 5.
    from jepsen_tpu.lin import synth

    return _timed_check(
        lambda: synth.generate_partitioned_register_history(
            100_000, seed=7, invoke_bias=0.45), 100_000, warm=False)


def _probe_wave_smoke():
    """Small-input probe of the never-on-chip host-row fast paths at
    the TOP host capacity — the rows*cap envelope neither program has
    run on this chip. Two legs over the window-34 pair-band witness
    shape (140 ops), PROVEN leg first so an experimental fault cannot
    cost its gating evidence: (1) WAVE — the round-7 K=4 program
    (bfs._host_closure_fixpoint_rows, scheduler forced off); (2)
    SCHED — the device-resident episode scheduler
    (bfs._host_sched_rows) under its ``sched`` result key. One
    seconds-scale fault-isolated run exercises exactly what the
    multi-hour partitioned rungs would; the ladder skips the wave
    rungs when leg 1 fails and the sched rung when either fails
    (probe-small-first, CLAUDE.md)."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import bfs, prepare, synth

    h = synth.generate_partitioned_register_history(
        140, concurrency=40, seed=0, partition_every=60,
        partition_len=20, max_crashes=10)
    p = prepare.prepare(m.cas_register(), h)

    def leg(sched: bool) -> dict:
        os.environ["JEPSEN_TPU_HOST_STICKY"] = "1"
        os.environ["JEPSEN_TPU_HOST_ROWS_K"] = "4"
        os.environ["JEPSEN_TPU_HOST_SCHED"] = "1" if sched else "0"
        t0 = time.time()
        r = bfs.check_packed(p, cap_schedule=(8,),
                             host_caps=bfs.HOST_ROW_CAPS[-1:])
        res = {"verdict": r.get("valid?"),
               "seconds": round(time.time() - t0, 1),
               "host_stats": r.get("host-stats")}
        if r.get("valid?") is not True:
            res["error"] = f"smoke verdict {r.get('valid?')!r}"
        return res

    out = {"events": len(h), "window": p.window,
           "host_cap": bfs.HOST_ROW_CAPS[-1]}
    out.update(leg(False))
    if "error" not in out \
            and not (out.get("host_stats") or {}).get("multi_rows"):
        out["error"] = "wave smoke ran no wave batches (vacuous probe)"
    sched = leg(True)
    if "error" not in sched \
            and not (sched.get("host_stats") or {}).get("sched_rows"):
        sched["error"] = ("sched smoke ran no scheduler episodes "
                          "(vacuous probe)")
    out["sched"] = sched
    # SCHED_QUEUE tuning leg (ISSUE 16): queue depth 64 at the top
    # host cap 2^19 keeps rows*cap at 2^25 — the same envelope the
    # spike executor proved at 32 rows x cap 2^20 (rows*cap program
    # complexity is the fault driver, round-2/3/5 lore), but a shape
    # this chip has never run. A clean leg lets the ladder's sched
    # rung run the deeper queue; a fault/wedge here gates it back to
    # the proven 32 without costing the multi-hour rung.
    q64: dict = {}
    if "error" not in sched:
        os.environ["JEPSEN_TPU_SCHED_QUEUE"] = "64"
        try:
            q64 = leg(True)
        finally:
            os.environ.pop("JEPSEN_TPU_SCHED_QUEUE", None)
        if "error" not in q64 \
                and not (q64.get("host_stats") or {}).get("sched_rows"):
            q64["error"] = ("q64 sched smoke ran no scheduler "
                            "episodes (vacuous probe)")
        q64["sched_queue"] = 64
    out["sched_q64"] = q64
    return out


def _probe_independent_keys():
    """BASELINE config 4: per-key registers decided as ONE vmapped
    device batch (lin.batched; independent.clj:246-296 checks keys one
    at a time on the JVM)."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import batched, synth

    n_keys, ops_per_key = 1000, 100
    subs = {k: synth.generate_register_history(
        ops_per_key, concurrency=5, seed=1000 + k, value_range=5,
        crash_prob=0.002, max_crashes=2) for k in range(n_keys)}
    model = m.cas_register()
    r = batched.try_check_batch(model, subs)    # warm/compile
    if r is None or len(r) < n_keys:
        raise RuntimeError(
            f"batch covered {0 if r is None else len(r)}/{n_keys} keys")
    t0 = time.time()
    r = batched.try_check_batch(model, subs)
    dt = time.time() - t0
    false_keys = sum(1 for v in r.values() if v["valid?"] is False)
    unknown_keys = sum(1 for v in r.values()
                       if v["valid?"] not in (True, False))
    n_ops = n_keys * ops_per_key
    return {"n_ops": n_ops, "n_keys": n_keys,
            # All histories are linearizable by construction: any False
            # is a checker bug, any non-bool an undecided key.
            "verdict": True if not (false_keys or unknown_keys)
            else ("unknown" if not false_keys else False),
            "false_keys": false_keys, "unknown_keys": unknown_keys,
            "analyzer": next(iter(r.values()))["analyzer"],
            "seconds": round(dt, 2),
            "ops_per_sec": round(n_ops / dt, 1)}


def _probe_service_c30():
    """Checker-as-a-service throughput (ROADMAP open item): N small
    mixed-shape histories — the majority binnable into shared vmapped
    programs, a minority odd shapes exercising the per-request
    fallthrough — queued through an IN-PROCESS daemon over real
    sockets by concurrent clients. Reports histories/s end to end
    (submit -> verdict on the wire) with p50/p99 latency plus the
    daemon's own stats (batch occupancy proves the bins actually
    batched; the XLA compile meter shows the warm-worker
    amortization). Fleet-shaped since ISSUE 13: a 2-worker pool with
    the request journal on and ONE injected worker-kill mid-run, so
    the artifact also prices the recovery path (worker_deaths /
    requeues / journal depth ride in ``service_stats``) — a kill must
    cost one requeue, never a verdict."""
    import os as _os
    import threading as _th

    from jepsen_tpu.lin import synth
    from jepsen_tpu.service.daemon import CheckerService
    from jepsen_tpu.service.protocol import CheckerClient

    journal = _os.path.join(".jax_cache", "bench_service.journal.jsonl")
    for f in (journal, journal + ".index.json"):
        try:
            _os.remove(f)
        except OSError:
            pass
    n_clients = 8
    jobs: list[tuple[str, object]] = []
    # Majority bin: one traced shape (same concurrency/length bucket).
    for i in range(90):
        jobs.append(("cas-register", synth.generate_register_history(
            100, concurrency=4, seed=9000 + i, value_range=5,
            crash_prob=0.01, max_crashes=2)))
    # Second bin: mutex histories (different kernel, still binnable).
    for i in range(20):
        jobs.append(("mutex", synth.generate_mutex_history(
            80, concurrency=4, seed=500 + i)))
    # Odd shapes: wide-window registers past the dense plan — the
    # slow-path fallthrough the scheduler must attribute, not hide.
    for i in range(10):
        jobs.append(("cas-register", synth.generate_register_history(
            120, concurrency=24, seed=100 + i, value_range=5)))
    n_jobs = len(jobs)

    svc = CheckerService("127.0.0.1", 0, flush_ms_=40, workers=2,
                         journal=journal).start()
    lock = _th.Lock()
    latencies: list[float] = []
    verdicts = {"true": 0, "false": 0, "unknown": 0}
    job_iter = iter(list(enumerate(jobs)))

    def client_loop():
        c = CheckerClient("127.0.0.1", svc.port)
        while True:
            with lock:
                nxt = next(job_iter, None)
            if nxt is None:
                break
            i, (model_name, h) = nxt
            t1 = time.time()
            r = c.submit(model_name, h, req_id=i)
            dt = time.time() - t1
            v = r.get("valid?")
            with lock:
                latencies.append(dt)
                verdicts["true" if v is True else
                         "false" if v is False else "unknown"] += 1
        c.close()

    # Warm pass: one of each bin shape compiles its programs so the
    # timed pass measures the amortized steady state the daemon
    # actually serves (cold-compile numbers are in xla_compile_s).
    warm = CheckerClient("127.0.0.1", svc.port)
    for model_name, h in (jobs[0], jobs[90], jobs[110]):
        warm.submit(model_name, h)
    warm.close()

    # One worker dies mid-run (the chaos hook): its in-hand bin must
    # requeue once and decide — visible in the stats, not the verdicts.
    svc.inject_worker_kill(1)
    t0 = time.time()
    threads = [_th.Thread(target=client_loop) for _ in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t0
    stats = None
    try:
        c = CheckerClient("127.0.0.1", svc.port)
        stats = c.stats()
        c.close()
    finally:
        svc.stop()
    # One percentile definition for the artifact AND the daemon's own
    # stats (they must never silently diverge).
    def pct(q):
        p = CheckerService._percentile(latencies, q)
        return None if p is None else round(p, 4)

    out = {"n_histories": n_jobs, "n_clients": n_clients,
           "seconds": round(wall, 2),
           "histories_per_sec": round(n_jobs / wall, 1),
           "latency_p50_s": pct(0.50), "latency_p99_s": pct(0.99),
           "verdicts": verdicts, "service_stats": stats}
    # All inputs are linearizable by construction; any False is a
    # checker bug, any unknown an undecided/failed request.
    out["verdict"] = (True if verdicts["false"] == 0
                      and verdicts["unknown"] == 0 else
                      "unknown" if verdicts["false"] == 0 else False)
    occ = (stats or {}).get("avg_occupancy")
    if not occ or occ <= 1:
        out["note"] = ("batch occupancy <= 1: bins did not share "
                       "device programs (vacuous batching)")
    st = stats or {}
    out["fleet"] = {k: st.get(k) for k in
                    ("workers", "worker_deaths", "worker_respawns",
                     "requeues", "journal_depth", "journal_settles")}
    # The daemon's process-wide pack meter (svc-request satellite,
    # ISSUE 16): host seconds spent packing across every request this
    # process served, forwarded into the ledger record by _probe_main.
    if st.get("pack_seconds") is not None:
        out["pack"] = {"pack_seconds": st["pack_seconds"]}
    if st.get("journal_depth"):
        out["note_fleet"] = (f"journal depth {st['journal_depth']} "
                             f"after drain: requests LOST (bug)")

    # Fleet scaling leg (ISSUE 19): the mixed-traffic workers=1 vs
    # workers=8 bench runs in a CHILD on the 8-device CPU mesh — this
    # process holds the chip and must keep holding it; the child
    # forces the CPU platform itself (fleet_bench._force_cpu_mesh).
    import subprocess as _sp
    import sys as _sys

    from jepsen_tpu.service.chaos import _force_cpu_env

    try:
        proc = _sp.run(
            [_sys.executable, "-m", "jepsen_tpu.service.fleet_bench"],
            capture_output=True, text=True, timeout=900,
            env=_force_cpu_env())
        line = (proc.stdout or "").strip().splitlines()
        fleet_scaling = json.loads(line[-1]) if line else None
        if fleet_scaling is not None:
            # The artifact keeps the headline surface; the per-run
            # detail lives in the child's own perf-ledger record.
            out["fleet_scaling"] = {
                k: fleet_scaling.get(k) for k in
                ("ratio_8v1", "target_ratio", "capacity",
                 "stream_batch_max_occupancy", "ok", "note")}
            out["fleet_scaling"]["hps"] = {
                w: (fleet_scaling.get("runs", {}).get(w) or {})
                .get("histories_per_sec") for w in ("1", "8")}
            out["fleet_scaling"]["occupancy_8"] = \
                (fleet_scaling.get("runs", {}).get("8") or {}) \
                .get("occupancy")
            if not fleet_scaling.get("ok"):
                out["verdict"] = "unknown"
                out["note_scaling"] = "fleet bench gate failed"
        else:
            out["fleet_scaling"] = {
                "error": f"no output (rc {proc.returncode})"}
    except Exception as e:  # noqa: BLE001 - the probe's other legs
        out["fleet_scaling"] = {"error": repr(e)}  # must still land
    return out


def _probe_stream_c30():
    """Streaming incremental checking (ISSUE 11 / ROADMAP online-mode
    unlock, doc/streaming.md): the 5k-op partitioned witness history
    checked (a) one-shot post-hoc and (b) streamed in increments with
    the frontier carried between them — same verdict, plus the numbers
    post-hoc checking cannot have: ingest-vs-checked lag and, on a
    corrupted twin, ABORT LATENCY (how many ops after the offending
    completion the stream needed before latching the witness, and how
    many ops of remaining traffic it saved). Ordered BEFORE
    partitioned_c30 and fault-isolated in its own subprocess so a
    stream fault cannot shadow the headline."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import device_check_packed, prepare, synth
    from jepsen_tpu.stream import StreamChecker

    n_ops = 5000
    h = list(synth.generate_partitioned_register_history(
        n_ops, seed=7, invoke_bias=0.45))
    prepare.reset_pack_stats()
    p = prepare.prepare(m.cas_register(), h)
    device_check_packed(p)                      # warm/compile
    t0 = time.time()
    one = device_check_packed(p)
    oneshot_s = time.time() - t0

    incr_events = 250
    t0 = time.time()
    sc = StreamChecker(m.cas_register(), min_rows=64)
    max_lag = 0
    for i in range(0, len(h), incr_events):
        st = sc.append(h[i:i + incr_events])
        max_lag = max(max_lag, st["settled"] - st["row"])
    t_fin = time.time()
    res = sc.finalize()
    stream_s = time.time() - t0
    finalize_s = time.time() - t_fin

    # Abort latency on a corrupted twin: find the corruption, stream
    # toward it, measure how far past it the stream ran before the
    # latch fired.
    bad = list(synth.corrupt_history(
        synth.generate_partitioned_register_history(
            n_ops, seed=7, invoke_bias=0.45), seed=3))
    bad_at = next(i for i, (a, b) in enumerate(zip(h, bad))
                  if a.value != b.value or a.type != b.type)
    sc2 = StreamChecker(m.cas_register(), min_rows=64)
    abort_after_ops = abort_s = None
    t_bad = None
    for i in range(0, len(bad), incr_events):
        # Clock starts when the offending completion is HANDED to the
        # session (before the append that carries it), so abort_s
        # covers the increment that catches it.
        if t_bad is None and i + incr_events > bad_at:
            t_bad = time.time()
        st = sc2.append(bad[i:i + incr_events])
        if sc2.aborted:
            abort_after_ops = i + incr_events - bad_at
            abort_s = time.time() - t_bad
            break
    saved_ops = len(bad) - (bad_at + (abort_after_ops or 0))
    resb = sc2.finalize()

    out = {"n_ops": n_ops, "window": p.window,
           "crashed": len(p.crashed_ops),
           "oneshot_verdict": one.get("valid?"),
           "oneshot_seconds": round(oneshot_s, 2),
           "stream_verdict": res.get("valid?"),
           "stream_seconds": round(stream_s, 2),
           "finalize_seconds": round(finalize_s, 3),
           "increments": (res.get("stream") or {}).get("increments"),
           "max_lag_rows": max_lag,
           "degraded": (res.get("stream") or {}).get("degraded"),
           "abort_verdict": resb.get("valid?"),
           "abort_after_ops": abort_after_ops,
           "abort_seconds": None if abort_s is None
           else round(abort_s, 3),
           "ops_saved_by_abort": saved_ops}
    # Pack cost split (ISSUE 16): the one-shot full pack vs the
    # stream sessions' per-increment settled-row packs (incr_s — the
    # sublinear path the vectorized settle bought), with the packer
    # mode, forwarded into the ledger record by _probe_main.
    st_pack = prepare.pack_stats()
    out["pack"] = {"prepare_s": round(st_pack["prepare_s"], 3),
                   "incr_s": round(st_pack["incr_s"], 3),
                   "incr_calls": st_pack["incr_calls"],
                   "mode": st_pack["mode"]}
    # Contract: parity with the one-shot verdict, and the injected
    # violation aborts the stream before the history runs out.
    out["verdict"] = (one.get("valid?") is True
                      and res.get("valid?") is True
                      and resb.get("valid?") is False
                      and abort_after_ops is not None
                      and saved_ops > 0)
    if not out["verdict"]:
        out["error"] = "stream probe contract failed (see fields)"
    return out


def _probe_txn_c30():
    """Transactional anomaly checking at the 100k-op scale (ISSUE 9 /
    ROADMAP scenario diversity): a concurrency-30 list-append history
    checked for serializability by the txn dependency-graph engine
    (jepsen_tpu.txn, doc/txn.md). Two legs: the HEALTHY history (the
    backward-edge window proves acyclicity host-side — measures
    edge-inference + pack throughput), then the SAME history with
    spliced anomalies, whose cycles the device SCC program must find
    and classify with oracle parity (the real device leg; its cost and
    tier stats ride in the artifact)."""
    from jepsen_tpu import txn
    from jepsen_tpu.txn import pack as txn_pack
    from jepsen_tpu.txn import synth

    txn_pack.reset_pack_stats()
    n_txns = 50_000
    h = synth.generate_list_append_history(
        n_txns, concurrency=30, keys=32, seed=7, crash_prob=0.0005)
    n_ops = len(h)

    t0 = time.time()
    healthy = txn.check(h, consistency="serializable", algorithm="tpu")
    healthy_s = time.time() - t0

    bad = synth.splice_anomaly(
        synth.splice_anomaly(h, "G2-item", seed=3, n=2),
        "G-single", seed=5)
    t0 = time.time()
    seeded = txn.check(bad, consistency="serializable", algorithm="tpu")
    seeded_s = time.time() - t0
    t0 = time.time()
    oracle_r = txn.check(bad, consistency="serializable",
                         algorithm="cpu")
    oracle_s = time.time() - t0

    stats = seeded.get("device-stats") or {}
    edges = stats.get("edges") or 0
    found = sorted(seeded.get("anomaly-types") or [])
    parity = found == sorted(oracle_r.get("anomaly-types") or []) \
        and seeded.get("anomalies") == oracle_r.get("anomalies")
    out = {
        "n_ops": n_ops, "n_txns": n_txns, "edges": edges,
        "healthy_verdict": healthy.get("valid?"),
        "healthy_seconds": round(healthy_s, 2),
        "edges_per_sec": round(edges / seeded_s, 1) if seeded_s else None,
        "seeded_verdict": seeded.get("valid?"),
        "seeded_seconds": round(seeded_s, 2),
        "oracle_seconds": round(oracle_s, 2),
        "anomaly_types": found,
        "anomaly_counts": {k: len(v) for k, v in
                           (seeded.get("anomalies") or {}).items()},
        "witness_parity": parity,
        "device_stats": stats,
        "fallbacks": seeded.get("fallbacks"),
        # Version-order join pack cost across all three legs
        # (ISSUE 16): the vectorized join's wall, forwarded into the
        # ledger record by _probe_main.
        "pack": {"pack_s": round(txn_pack.pack_stats()["pack_s"], 3),
                 "pack_calls": txn_pack.pack_stats()["pack_calls"]}}
    # Contract: healthy decides valid, every spliced anomaly class is
    # found, and the device classification matches the oracle.
    out["verdict"] = (healthy.get("valid?") is True
                      and seeded.get("valid?") is False
                      and {"G2-item", "G-single"} <= set(found)
                      and parity)
    if not out["verdict"]:
        out["error"] = "txn probe contract failed (see fields)"
    return out


def _probe_mesh_c30():
    """Crash-dom MESH rung (ISSUE 18): the sharded compact band
    (lin/sharded.py, doc/sharding.md) driven over every visible
    device, fault-ISOLATED in its own subprocess and ordered before
    the partitioned ladder so a mesh fault can never cost the
    single-chip config-5 number. Mesh env knobs are FORCED per leg so
    the rung measures the documented defaults, not whatever the
    driver environment happens to export. Legs, proven-small-first
    per the fault lore: (0) the window-34 pair-band config-5 witness
    (140 ops) — the scaled shape the crash-dom tests pin, seconds-
    scale, any fault dies here; (1) the timed 5k partitioned shape
    (window 25, single-key crash-dom band). Both legs attach the
    per-device mesh-stats (dispatches, dispatch wall, peak shard
    occupancy) that _probe_main forwards into the bench artifact and
    the perf-ledger record — the before/after evidence for the
    config-5 3217 s -> <600 s mesh target reads from here."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from jepsen_tpu import models as m
    from jepsen_tpu.lin import prepare, sharded, synth

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("d",))
    out = {"devices": len(devs),
           "platform": devs[0].platform}
    forced = {"JEPSEN_TPU_MESH_CAPS": "16384,65536,262144",
              "JEPSEN_TPU_MESH_PREPRUNE": "1",
              "JEPSEN_TPU_MESH_IT_MAX": "0"}
    saved = {k: os.environ.get(k) for k in forced}
    os.environ.update(forced)
    try:
        # Leg 0: small-input witness smoke (CLAUDE.md fault lore —
        # probe new mesh shapes on SMALL inputs first).
        hs = synth.generate_partitioned_register_history(
            140, concurrency=40, seed=0, partition_every=60,
            partition_len=20, max_crashes=10)
        ps = prepare.prepare(m.cas_register(), hs)
        t0 = time.time()
        r = sharded.check_packed(ps, mesh=mesh,
                                 cap_schedule=(64, 512),
                                 engine="sparse")
        out["smoke"] = {"events": len(hs), "window": ps.window,
                        "verdict": r.get("valid?"),
                        "seconds": round(time.time() - t0, 1),
                        "mesh_stats": r.get("mesh-stats")}
        if r.get("valid?") is not True:
            out["error"] = \
                f"mesh witness smoke verdict {r.get('valid?')!r}"
            return out

        # Leg 1: the 5k partitioned shape, timed (first run — the
        # persistent compile cache amortizes compiles cross-process,
        # the long-probe _timed_check warm=False precedent).
        h = synth.generate_partitioned_register_history(
            5000, seed=7, invoke_bias=0.45)
        p = prepare.prepare(m.cas_register(), h)
        t0 = time.time()
        r = sharded.check_packed(p, mesh=mesh, engine="sparse")
        dt = time.time() - t0
        out.update({"n_ops": 5000, "window": p.window,
                    "crashed": len(p.crashed_ops),
                    "verdict": r.get("valid?"),
                    "analyzer": r.get("analyzer"),
                    "timed_run": "first",
                    "seconds": round(dt, 1),
                    "ops_per_sec": round(5000 / dt, 1),
                    # the per-device evidence sub-dict: _probe_main
                    # forwards it into the perf-ledger record.
                    "mesh": r.get("mesh-stats")})
        if r.get("valid?") is not True:
            out["error"] = \
                f"5k partitioned mesh verdict {r.get('valid?')!r}"
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def _probe_pack():
    """Chip-free pack micro-rung (ISSUE 16): the literal config-5
    100k-op history packed under BOTH packer modes — the vectorized
    pipeline (JEPSEN_TPU_FAST_PACK=1, the default) against the Python
    spec walk — with bit-parity asserted (supervise.history_fingerprint
    covers every packed array the fingerprint hashes; slot_op is
    compared explicitly because the fingerprint excludes it) and the
    speedup recorded. The perf-ledger record this rung appends is the
    standing before/after pack-wall evidence `cli.py perf report`
    shows. Never needs the chip — packing is pure numpy — and the cpu
    platform is forced anyway so an accidental device init cannot take
    the TPU ahead of the real probes (this rung runs FIRST)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from jepsen_tpu import models as m
    from jepsen_tpu.lin import prepare, supervise, synth

    h = list(synth.generate_partitioned_register_history(
        100_000, seed=7, invoke_bias=0.45))
    model = m.cas_register()

    def one(mode):
        os.environ["JEPSEN_TPU_FAST_PACK"] = mode
        # The spec leg must be the PYTHON walk: NATIVE_PACK=1 would
        # swap in the ctypes slot walk and the "py" wall would measure
        # the wrong baseline (doc/env.md § JEPSEN_TPU_NATIVE_PACK).
        os.environ["JEPSEN_TPU_NATIVE_PACK"] = mode
        prepare.reset_pack_stats()
        t0 = time.time()
        p = prepare.prepare(model, list(h))
        return p, time.time() - t0, prepare.pack_stats()["mode"]

    # Interleaved best-of-3 per leg (the headline's best-of-3 habit):
    # shared-box CPU throughput swings tens of percent run to run, and
    # interleaving the legs makes a throttled window tax both modes
    # instead of whichever leg it landed on.
    vec_runs: list = []
    py_runs: list = []
    try:
        for _ in range(3):
            p_vec, w, vec_mode = one("1")
            vec_runs.append(w)
            p_py, w, py_mode = one("0")
            py_runs.append(w)
    finally:
        os.environ.pop("JEPSEN_TPU_FAST_PACK", None)
        os.environ.pop("JEPSEN_TPU_NATIVE_PACK", None)
    vec_s, py_s = min(vec_runs), min(py_runs)
    parity = (supervise.history_fingerprint(p_vec)
              == supervise.history_fingerprint(p_py)
              and np.array_equal(np.asarray(p_vec.slot_op),
                                 np.asarray(p_py.slot_op)))
    speedup = round(py_s / vec_s, 2) if vec_s else None

    # Device leg (ISSUE 20, lin/pack_dev.py): K=8 same-shape lanes
    # materialized three ways from the SAME prepacks — host finish
    # (PACK_DEV=0), per-lane device dispatches, one batched vmapped
    # dispatch — parity-checked against each other and timed best-of-3
    # after a warmup dispatch absorbs the compile. The batched-vs-
    # single gain is the amortization the daemon's bin waves ride.
    from jepsen_tpu.lin import pack_dev

    def _fp(p):
        return (supervise.history_fingerprint(p),
                np.asarray(p.slot_op).tobytes())

    K = 8
    hk = list(synth.generate_partitioned_register_history(
        20_000, seed=9, invoke_bias=0.45))
    pres = [pack_dev.prepack(model, list(hk)) for _ in range(K)]

    def _leg(dev, batched):
        os.environ["JEPSEN_TPU_PACK_DEV"] = "1" if dev else "0"
        if batched:
            return pack_dev.materialize_batch(list(pres))
        return [pack_dev.materialize(p) for p in pres]

    try:
        _leg(True, True)                       # warm the compile
        runs: dict[str, list[float]] = {"host": [], "single": [],
                                        "batched": []}
        outs: dict[str, list] = {}
        for _ in range(3):
            for name, dev, batched in (("host", False, False),
                                       ("single", True, False),
                                       ("batched", True, True)):
                t0 = time.time()
                outs[name] = _leg(dev, batched)
                runs[name].append(time.time() - t0)
    finally:
        os.environ.pop("JEPSEN_TPU_PACK_DEV", None)
    host_s, single_s, batched_s = (min(runs[k]) for k in
                                   ("host", "single", "batched"))
    want = _fp(outs["host"][0])
    dev_parity = all(_fp(p) == want
                     for leg in outs.values() for p in leg)
    dev_speedup = round(host_s / batched_s, 2) if batched_s else None
    batch_gain = round(single_s / batched_s, 2) if batched_s else None
    out = {"n_ops": len(h) // 2, "n_events": len(h),
           "return_events": int(p_vec.R),
           "window": p_vec.window,
           "vec_seconds": round(vec_s, 3), "vec_mode": vec_mode,
           "vec_seconds_runs": [round(w, 3) for w in vec_runs],
           "py_seconds": round(py_s, 3), "py_mode": py_mode,
           "py_seconds_runs": [round(w, 3) for w in py_runs],
           "speedup": speedup, "bit_parity": parity,
           "dev_k": K,
           "dev_host_seconds": round(host_s, 3),
           "dev_single_seconds": round(single_s, 3),
           "dev_batched_seconds": round(batched_s, 3),
           "dev_speedup": dev_speedup,
           "dev_batch_gain": batch_gain,
           "dev_bit_parity": dev_parity,
           # pack sub-dict: _probe_main forwards it into the ledger
           # record so `perf report`/`perf diff` trend the pack wall.
           "pack": {"prepare_s": round(vec_s, 3), "mode": vec_mode,
                    "py_s": round(py_s, 3), "speedup": speedup,
                    "dev_batched_s": round(batched_s, 3),
                    "dev_speedup": dev_speedup,
                    "dev_batch_gain": batch_gain}}
    if dev_speedup is not None and dev_speedup < 2.0:
        # Honest record (ISSUE 20 acceptance): the batched device
        # pack did not clear 2x over the host finish here. On the
        # forced-CPU mesh that is EXPECTED — the per-dispatch cost
        # batching amortizes is the TPU tunnel's ~100 ms round trip
        # (CLAUDE.md), which the cpu backend does not pay, so the
        # numpy finish wins outright and dev_batch_gain sits near 1.
        # The ledger keeps trending both so a real-chip run of this
        # rung shows the amortization where it exists.
        import jax as _jax

        out["pack"]["dev_note"] = (
            f"batched device pack {dev_speedup}x vs host finish at "
            f"K={K} on {_jax.devices()[0].platform}: no tunnel "
            "dispatch overhead to amortize on this backend")
    # Contract: bit-parity always; the ISSUE 16 acceptance floor is
    # >=5x on this shape, but the probe's own soft gate is 3x so a
    # noisy shared box flags degradation without flapping the rung.
    out["verdict"] = bool(parity and dev_parity
                          and speedup and speedup >= 3.0)
    if not out["verdict"]:
        out["error"] = "pack parity/speedup contract failed (see fields)"
    return out


def _probe_fused_pair():
    """Env-gated probe of the PAIR-KEY fused fixpoint tier at the
    raised candidate-space bound (JEPSEN_TPU_PSORT_FUSED_MAX_N,
    psort_fused.max_n) — fault-ISOLATED in its own subprocess rung,
    ordered before the partitioned ladder, so a Mosaic fault in the
    never-probed raised shape can never cost the headline. The shape
    under test is the CRASH-FREE saturated pair band: crash_dom
    histories (every partitioned rung) keep use_fused=0 by design
    (round-5 forced-lax lore), so this standalone probe is the only
    place the raised tier can honestly engage. Legs, proven-first per
    the fault lore: (0) a seconds-scale 140-op small-input smoke at a
    single big cap — the raised-bound tier programs compile and any
    reached tier dispatches HERE, where a fault costs seconds; then
    the timed A/B/A over a 500-op window-~26 history: unfused chain,
    fused at the proven default bound (2^19), fused at the raised
    bound (MAX_N=20). Verdict parity across all legs is the contract;
    max_cap and walls are recorded honestly (when the frontier never
    reaches a raised tier, equal walls ARE the honest A/B result)."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import bfs, prepare, synth

    # Small-input probe first (CLAUDE.md fault lore: probe new kernel
    # shapes on SMALL inputs before spending budget on them).
    hs = synth.generate_register_history(
        140, concurrency=40, seed=3, value_range=5, crash_prob=0)
    ps = prepare.prepare(m.cas_register(), hs)
    os.environ["JEPSEN_TPU_PSORT_FUSED"] = "1"
    os.environ["JEPSEN_TPU_PSORT_FUSED_MAX_N"] = "20"
    t0 = time.time()
    r = bfs.check_packed(ps, cap_schedule=(1 << 15,))
    out = {"smoke": {"events": len(hs), "window": ps.window,
                     "verdict": r.get("valid?"),
                     "seconds": round(time.time() - t0, 1)}}
    if r.get("valid?") is not True:
        out["error"] = f"raised-bound smoke verdict {r.get('valid?')!r}"
        return out

    h = synth.generate_register_history(
        500, concurrency=40, seed=7, value_range=5, crash_prob=0)
    p = prepare.prepare(m.cas_register(), h)
    b = max(len(p.unintern), 2).bit_length()
    out.update({"n_ops": len(h), "window": p.window,
                "pair_keys": p.window + b > 31})

    def leg(fused, max_exp=None):
        os.environ["JEPSEN_TPU_PSORT_FUSED"] = "1" if fused else "0"
        if max_exp:
            os.environ["JEPSEN_TPU_PSORT_FUSED_MAX_N"] = str(max_exp)
        else:
            os.environ.pop("JEPSEN_TPU_PSORT_FUSED_MAX_N", None)
        bfs.check_packed(p)                     # warm/compile
        t0 = time.time()
        rr = bfs.check_packed(p)
        return {"verdict": rr.get("valid?"),
                "seconds": round(time.time() - t0, 2),
                "max_cap": rr.get("max-cap")}

    try:
        out["unfused"] = leg(False)
        out["fused_default"] = leg(True)
        out["fused_raised"] = leg(True, max_exp=20)
    finally:
        os.environ.pop("JEPSEN_TPU_PSORT_FUSED", None)
        os.environ.pop("JEPSEN_TPU_PSORT_FUSED_MAX_N", None)
    verdicts = {out[k]["verdict"]
                for k in ("unfused", "fused_default", "fused_raised")}
    out["verdict"] = verdicts == {True}
    if not out["verdict"]:
        out["error"] = "fused-pair legs disagree (see fields)"
    return out


PROBES = {"ping": _probe_ping, "mutex_c30": _probe_mutex_c30,
          "txn_c30": _probe_txn_c30,
          "wide_window_c30": _probe_wide_window_c30,
          "partitioned_c30": _probe_partitioned_c30,
          "independent_keys": _probe_independent_keys,
          "wave_smoke": _probe_wave_smoke,
          "service_c30": _probe_service_c30,
          "stream_c30": _probe_stream_c30,
          "pack": _probe_pack, "fused_pair": _probe_fused_pair,
          "mesh_c30": _probe_mesh_c30}


def _run_probe_subprocess(key: str, timeout: int, env_extra=None,
                          stall_s: float = STALL_S, argv=None):
    """Run one probe isolated in a child process under the stall
    watchdog; returns (result_dict, why) with why in (None, "timeout",
    "stall"). The child's LAST non-heartbeat stdout line is its json
    result; "HB <n>" lines carry the engine liveness counter, and the
    watchdog kills the child when the counter stops advancing (no new
    output of any kind) for ``stall_s`` — a wedged tunnel dispatch,
    not a slow search. ``argv``/``env_extra``/``stall_s`` are test and
    experiment hooks (the SYNC_CHUNKS gating run passes env_extra)."""
    env = dict(os.environ)
    env.update(env_extra or {})
    cmd = argv or [sys.executable, os.path.abspath(__file__),
                   "--probe", key]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env)
    lines: list[str] = []
    state = {"last_activity": time.time(), "last_hb": None}

    def _read_stdout():
        for ln in proc.stdout:
            ln = ln.rstrip("\n")
            if not ln.strip():
                continue
            if ln.startswith("HB "):
                # Heartbeats prove the PROCESS is alive; only a CHANGED
                # progress value proves dispatches are completing.
                try:
                    v = int(ln.split()[1])
                except (IndexError, ValueError):
                    continue
                if v != state["last_hb"]:
                    state["last_hb"] = v
                    state["last_activity"] = time.time()
                continue
            lines.append(ln)
            state["last_activity"] = time.time()

    def _read_stderr():
        # Line-wise, and each line resets the stall clock: the kill
        # condition is "no new output of ANY kind" — a probe in a long
        # silent dispatch that is still logging runtime warnings to
        # stderr is alive, not wedged.
        err_lines = []
        for ln in proc.stderr:
            err_lines.append(ln)
            state["last_activity"] = time.time()
        state["stderr"] = "".join(err_lines)

    t_out = threading.Thread(target=_read_stdout, daemon=True)
    t_err = threading.Thread(target=_read_stderr, daemon=True)
    t_out.start()
    t_err.start()
    t0 = time.time()
    why = None
    while proc.poll() is None:
        now = time.time()
        if now - t0 > timeout:
            why = "timeout"
            break
        if now - state["last_activity"] > stall_s:
            why = "stall"
            break
        time.sleep(0.2)
    kill_info = None
    if why is not None:
        # SIGTERM -> SIGKILL escalation, all of it RECORDED: a wedged
        # teardown used to survive a bare kill() race and leave the
        # child alive with no trace of the event in the artifact. The
        # record carries the last heartbeat progress value so triage
        # can see how far the engine got before the wedge.
        kill_info = {"why": why, "last_hb": state["last_hb"],
                     "silent_s": round(
                         time.time() - state["last_activity"], 1)}
        proc.terminate()
        try:
            proc.wait(timeout=KILL_GRACE_S)
            kill_info["sigkill"] = False
        except subprocess.TimeoutExpired:
            proc.kill()
            kill_info["sigkill"] = True
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                # Should be impossible (SIGKILL), but a kernel-stuck
                # child must be visible, not silently abandoned.
                kill_info["unkillable"] = True
    else:
        proc.wait()
    t_out.join(timeout=5)
    t_err.join(timeout=5)
    # A result already on the pipe wins over the kill reason: a probe
    # that PRINTED its answer and then wedged in teardown (the
    # shared-chip tunnel wedge can hit the exit path too) completed —
    # discarding the answer and re-running would burn the remaining
    # budget re-deriving a number we already hold. Scan backwards past
    # any post-result noise (teardown messages, a partial line flushed
    # at kill) for the last parseable JSON object.
    for ln in reversed(lines):
        if not ln.lstrip().startswith("{"):
            continue
        try:
            r = json.loads(ln)
            if isinstance(r, dict) and kill_info is not None:
                # Completed result recovered from a child that then
                # had to be killed in teardown: record the kill.
                r["teardown_kill"] = kill_info
            return r, None
        except json.JSONDecodeError:
            continue
    # no_child_result marks a PARENT-synthesized error: the child died
    # without printing (and so without writing its perf-ledger record
    # — the record happens just before the print). _run_probe records
    # on its behalf, or a persistently wedging probe would read green
    # to `perf gate` forever.
    if why == "timeout":
        return {"error": f"probe timed out after {timeout}s",
                "kill": kill_info, "no_child_result": True}, why
    if why == "stall":
        return {"error": (f"probe stalled: no progress for "
                          f"{int(stall_s)}s (wedged dispatch), "
                          "killed"), "kill": kill_info,
                "no_child_result": True}, why
    tail = (state.get("stderr", "") + "\n".join(lines))[-2000:]
    return {"error": f"probe exited rc={proc.returncode}: {tail}",
            "no_child_result": True}, None


def _run_probe(key: str, timeout: int, env_extra=None,
               stall_s: float = STALL_S):
    """_run_probe_subprocess + ONE kill-and-retry on a stall (the
    shared-chip tunnel wedge is transient; a wedged probe should cost
    its detection window, not its full budget). The retry gets the
    budget that remains and is recorded in the artifact. A FINAL
    result whose child died without printing is ledger-recorded here
    on the child's behalf (see no_child_result) — error evidence the
    perf gate's error-appeared/still-erroring rules need."""
    t0 = time.time()
    r, why = _run_probe_subprocess(key, timeout, env_extra=env_extra,
                                   stall_s=stall_s)
    if why == "stall":
        first = r
        remaining = max(60, int(timeout - (time.time() - t0)))
        r, _ = _run_probe_subprocess(key, remaining,
                                     env_extra=env_extra,
                                     stall_s=stall_s)
        r["stall_retries"] = 1
        r["first_attempt"] = first
    if r.get("no_child_result"):
        try:
            from jepsen_tpu.obs import ledger as perf_ledger

            tag = (env_extra or {}).get("JEPSEN_TPU_PERF_TAG") \
                or os.environ.get("JEPSEN_TPU_PERF_TAG") or key
            perf_ledger.record(
                tag, kind="bench", verdict=None,
                error=str(r.get("error"))[:300],
                # The record must carry the RUNG's forced config, not
                # the parent's environment (the documented env/env_fp
                # schema — forensics on a wedged rung must name the
                # right knob set).
                env_overlay=env_extra,
                extra={"recorded_by": "parent",
                       "kill": r.get("kill")})
        except Exception:  # noqa: BLE001 - loss-proof contract
            pass
    return r


def _verify_recovery() -> bool:
    """After a probe failure (possible worker crash), wait out the
    restart and prove the chip answers again."""
    for _ in range(3):
        time.sleep(WORKER_RESTART_S)
        r, _why = _run_probe_subprocess("ping", 120)
        if r.get("ok"):
            return True
    return False


def _wide_probes(detail: dict, out: dict, t_start: float) -> None:
    """BASELINE config 3-5 probes (skippable via JEPSEN_TPU_BENCH_WIDE=0),
    each in its own subprocess, safe-first (see module docstring). The
    full result line is RE-emitted after every probe so an external
    timeout mid-sequence still leaves every completed probe on stdout.

    partitioned_c30 runs an ATTEMPT LADDER, most experimental first,
    each rung fault-isolated in its own subprocess with its config
    recorded so failures archive as gating evidence instead of erasing
    the headline. The ladder peels the host-row executor axes off
    one at a time, so a fault names its own culprit and the final rung
    is always a shape already proven on this chip. The sched/wave
    rungs are additionally gated by the two-leg ``wave_smoke``
    pre-probe — the K-row wave AND the episode-scheduler programs on
    the SMALL window-34 witness shape at the top host cap
    (probe-small-first, CLAUDE.md): if the seconds-scale probe fails,
    the matching rungs are skipped (recorded) instead of spending
    multi-hour budgets discovering the same fault. The rungs:
    (1) ``sched`` — the device-resident episode scheduler
    (JEPSEN_TPU_HOST_SCHED=1, ~1 dispatch per clean episode; the
    kill-the-tunnel tentpole) over sticky caps at the conservative
    SYNC_CHUNKS=2 so a scheduler fault is isolated from every other
    axis; (2) ``wave8`` — sticky caps + K=4 fused wave batches +
    SYNC_CHUNKS=8 (the full round-7 configuration, including the
    round-6 queue-depth re-test); (3) ``wave`` — the same at the
    conservative SYNC_CHUNKS=2, so a wave fault is separated from a
    queue-depth fault; (4) ``sticky`` — sticky caps only (K=1: no
    never-probed device program, the wave's host-side scheduling
    half); (5) ``r6`` — the literal round-6 fused shape (sticky off,
    K=1); (6) ``unfused`` — FUSED_CLOSURE=0, the round-5 per-pass
    shape PROVEN to decide on this chip, so no experimental fault can
    cost the headline partitioned number. Every env var is forced
    explicitly on every rung (children inherit the parent env; an
    exported override must not run a rung at a config other than the
    one its artifact records; JEPSEN_TPU_PSORT_FUSED is forced 0 —
    the crash-dom band never engages the fused psort kernel, and the
    artifact must record that). Each rung's result carries
    ``host_stats`` (per-cap wall seconds, wasted escalation passes,
    sticky hit/miss, wave-batch and scheduler dispatch counts —
    bfs._host_rows), so the dispatch-drop factor and the residual
    cost profile read directly off the artifact."""
    if os.environ.get("JEPSEN_TPU_BENCH_WIDE", "1") == "0":
        return
    for i, (key, ceiling) in enumerate(PROBE_ORDER):
        if key == "partitioned_c30":
            def _rung(sync, fused, sticky, k, sched, tag):
                # Per-rung frontier checkpoint: a stall-killed child's
                # retry (and a bench re-run after an external kill)
                # RESUMES the partitioned decide mid-history instead of
                # restarting from op 0. Per-rung paths keep rung
                # timings honest (a rung never resumes another rung's
                # progress); the engine deletes the file on a definite
                # verdict and stamps resumed runs with
                # resumed_from_row.
                ck = os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    ".jax_cache", f"bench_partitioned_{tag}.ckpt.npz")
                return ({"JEPSEN_TPU_SYNC_CHUNKS": str(sync),
                         "JEPSEN_TPU_FUSED_CLOSURE": str(fused),
                         "JEPSEN_TPU_HOST_STICKY": str(sticky),
                         "JEPSEN_TPU_HOST_ROWS_K": str(k),
                         "JEPSEN_TPU_HOST_SCHED": str(sched),
                         # Queue depth is part of the rung's recorded
                         # config (forced-env invariant). The proven
                         # default; the smoke's q64 leg may promote
                         # the sched rung below (ISSUE 16 tuning).
                         "JEPSEN_TPU_SCHED_QUEUE": "32",
                         # The crash-dom band never engages the fused
                         # psort kernel; force it off so the artifact
                         # records the exact (inert-anyway) config.
                         "JEPSEN_TPU_PSORT_FUSED": "0",
                         # The static gate must never ROUTE a bench
                         # rung (an exported route mode would run a
                         # rung at a config other than the one its
                         # artifact records): force the observe-only
                         # default on every rung.
                         "JEPSEN_TPU_STATIC_GATE": "warn",
                         # Perf-ledger trend identity: each ladder
                         # rung records under its own tag so the
                         # sched/wave/unfused trajectories never mix
                         # in one trend row (obs/ledger).
                         "JEPSEN_TPU_PERF_TAG":
                             f"partitioned_c30.{tag}",
                         "JEPSEN_TPU_CKPT": ck},
                        {"sync_chunks": sync, "fused_closure": fused,
                         "host_sticky": sticky, "host_rows_k": k,
                         "host_sched": sched, "sched_queue": 32,
                         "checkpoint": ck}, tag)

            attempts = (
                _rung(2, 1, 1, 4, 1, "sched"),
                _rung(8, 1, 1, 4, 0, "wave8"),
                _rung(2, 1, 1, 4, 0, "wave"),
                _rung(2, 1, 1, 1, 0, "sticky"),
                _rung(2, 1, 0, 1, 0, "r6"),
                _rung(2, 0, 0, 1, 0, "unfused"),
            )
            # Probe-small-first gate (CLAUDE.md): the K-row wave
            # program has never run on this chip, so a seconds-scale
            # small-shape probe at the top host cap decides whether
            # the wave rungs may spend multi-hour budgets on it — a
            # wedge in an ungated rung would burn a full
            # PARTITIONED_STALL_S window (plus a retry) per rung.
            wave_ok = False
            sched_ok = False
            smoke_ran = False
            remaining = TOTAL_BUDGET_S - (time.time() - t_start)
            # Only run the smoke when a wave rung could still run
            # AFTER it at worst case — otherwise the smoke's budget
            # comes straight out of the proven final rung for a
            # gating decision nothing consumes.
            if remaining >= 2 * PARTITIONED_MIN_S + WAVE_SMOKE_BUDGET_S:
                smoke_ran = True
                smoke = _run_probe(
                    "wave_smoke", WAVE_SMOKE_BUDGET_S,
                    env_extra={"JEPSEN_TPU_SYNC_CHUNKS": "2",
                               "JEPSEN_TPU_FUSED_CLOSURE": "1",
                               "JEPSEN_TPU_HOST_STICKY": "1",
                               "JEPSEN_TPU_HOST_ROWS_K": "4",
                               "JEPSEN_TPU_PSORT_FUSED": "0",
                               "JEPSEN_TPU_STATIC_GATE": "warn",
                               "JEPSEN_TPU_PERF_TAG": "wave_smoke"},
                    stall_s=WAVE_SMOKE_BUDGET_S / 2)
                detail["wave_smoke"] = smoke
                _emit(out)
                wave_ok = "error" not in smoke
                sched_leg = smoke.get("sched") or {}
                # The sched rung also runs K=4 waves as its fallback
                # rung, so it needs BOTH legs clean.
                sched_ok = wave_ok and bool(sched_leg) \
                    and "error" not in sched_leg
                # SCHED_QUEUE tuning (ISSUE 16): the sched rung runs
                # queue depth 64 only when the smoke's q64 leg proved
                # that exact rows*cap envelope clean on THIS chip —
                # otherwise the proven 32 stands. The rung's env AND
                # tags both move so the artifact records the config
                # that actually ran.
                q64_leg = smoke.get("sched_q64") or {}
                if sched_ok and q64_leg and "error" not in q64_leg:
                    attempts[0][0]["JEPSEN_TPU_SCHED_QUEUE"] = "64"
                    attempts[0][1]["sched_queue"] = 64
                if not wave_ok or "error" in sched_leg:
                    # The smoke fault may have killed the worker; the
                    # remaining (non-wave) rungs need it back. A
                    # failed recovery abandons the whole ladder (the
                    # per-rung pattern below) — dispatching a rung at
                    # a dead worker burns its stall window for
                    # nothing, and detail[key] must still be
                    # populated for artifact consumers.
                    recovered = _verify_recovery()
                    smoke["worker_recovered"] = recovered
                    _emit(out)
                    if not recovered:
                        attempts = ()
                        r = {"error": ("wave smoke fault killed the "
                                       "TPU worker and it did not "
                                       "recover; partitioned ladder "
                                       "abandoned")}
            for a_i, (env_extra, tags, tag) in enumerate(attempts):
                last = a_i + 1 == len(attempts)
                remaining = TOTAL_BUDGET_S - (time.time() - t_start)
                if not last and remaining < 2 * PARTITIONED_MIN_S:
                    # Experimental rungs only run on real clock: an
                    # exhausted budget skips straight to the proven
                    # round-5 shape so the PARTITIONED_MIN_S floor is
                    # spent ONCE, on the rung most likely to land the
                    # headline (keeps the module docstring's
                    # one-floor-overrun exception honest).
                    skipped = dict(tags)
                    skipped["error"] = ("skipped: remaining budget "
                                       "reserved for the proven "
                                       "fallback rung")
                    detail[f"partitioned_c30_{tag}"] = skipped
                    continue
                if (tags.get("host_sched") and not sched_ok) or \
                        (tags["host_rows_k"] > 1 and not wave_ok):
                    # Honest skip reason: a smoke that FAILED is
                    # gating evidence against the wave/scheduler
                    # program; a smoke that never ran (no clock for
                    # it) is not.
                    skipped = dict(tags)
                    skipped["error"] = (
                        "skipped: wave/sched smoke probe failed "
                        "(probe-small-first)" if smoke_ran else
                        "skipped: no budget to smoke-probe the wave "
                        "program (probe-small-first)")
                    detail[f"partitioned_c30_{tag}"] = skipped
                    continue
                budget = _partitioned_budget(t_start, ceiling) if last \
                    else int(min(ceiling, remaining - PARTITIONED_MIN_S))
                # At floor-sized budgets the wide stall window cannot
                # fire before the timeout check (evaluated first) —
                # accepted: shrinking it instead would kill HEALTHY
                # fused dispatches, which legitimately freeze the HB
                # counter for many minutes, and a floor-budget retry
                # window would be too short to decide anyway.
                r = _run_probe(key, budget, env_extra=env_extra,
                               stall_s=PARTITIONED_STALL_S)
                r.update(tags)
                r["budget_seconds"] = budget
                if "error" not in r:
                    break
                # Archive the failed rung under its own key (the final
                # rung's result ALSO lands in detail[key] below, so
                # detail["partitioned_c30"] always exists).
                detail[f"partitioned_c30_{tag}"] = r
                if a_i + 1 >= len(attempts):
                    break
                recovered = _verify_recovery()
                r["worker_recovered"] = recovered
                _emit(out)
                if not recovered:
                    break
        else:
            # Cap the stall window below the probe budget, or the
            # timeout check (evaluated first) always wins and the
            # kill-and-retry path can never fire for these probes.
            # PERF_TAG is forced to the probe key (the rungs'
            # forced-env invariant): an exported override must not
            # collapse every probe's ledger record into one trend row.
            r = _run_probe(key, ceiling,
                           env_extra={"JEPSEN_TPU_PERF_TAG": key},
                           stall_s=min(STALL_S, ceiling / 2))
        detail[key] = r
        _emit(out)
        if "error" in r and i + 1 < len(PROBE_ORDER):
            # The fault may have killed the worker; recover before the
            # next probe so one crash cannot shadow later numbers.
            # (Skipped after the LAST probe: there is nothing left to
            # protect, and up to 3x WORKER_RESTART_S of recovery sleeps
            # would only delay the final emission the loss-proof
            # contract defends.)
            recovered = _verify_recovery()
            detail[key]["worker_recovered"] = recovered
            _emit(out)
            if not recovered:
                break


def _probe_main(key: str) -> None:
    from jepsen_tpu.util import enable_compile_cache

    enable_compile_cache()
    stop = threading.Event()
    lock = threading.Lock()
    # Cross-run perf ledger (jepsen_tpu.obs.ledger, doc/observability.md
    # § Perf ledger): snapshot the quarantine ledger NOW so the record
    # this probe appends can carry the delta it caused. Best-effort —
    # the ledger must never cost a probe result.
    q_before = {}
    try:
        from jepsen_tpu.lin import supervise as _sup

        q_before = dict(_sup.load_ledger())
    except Exception:  # noqa: BLE001 - observability only
        _sup = None
    t_probe = time.time()

    def _heartbeat():
        # "HB <progress>": the engines' liveness counter
        # (jepsen_tpu.util.progress ticks per completed dispatch
        # step). The parent watchdog kills this child only when the
        # VALUE stops changing — an alive process with a wedged
        # dispatch keeps printing the same number.
        from jepsen_tpu.util import progress

        while not stop.wait(HEARTBEAT_S):
            with lock:
                print(f"HB {progress()}", flush=True)

    threading.Thread(target=_heartbeat, daemon=True).start()
    try:
        r = PROBES[key]()
    except Exception:
        r = {"error": traceback.format_exc(limit=4)}
    wall_s = time.time() - t_probe
    stop.set()
    # Flight recorder: a probe run under JEPSEN_TPU_TRACE=1 attaches
    # its attribution summary (per-site wall seconds, compile time,
    # tunnel estimate) to the JSON artifact and flushes the JSONL
    # spill so `cli.py trace report` reads the finished run
    # (doc/observability.md; `make probe-config5` sets this up).
    try:
        from jepsen_tpu.obs import report as obs_report
        from jepsen_tpu.obs import trace as obs_trace

        if obs_trace.enabled() and isinstance(r, dict) \
                and (obs_trace.spilled() or obs_trace.events()):
            # The event guard keeps a zero-span run (e.g. an error
            # before the first dispatch) from attaching a PREVIOUS
            # run's stale spill file as its own attribution.
            spill = obs_trace.flush()
            evs = obs_report.load(spill) if spill \
                else obs_trace.events()
            r["trace"] = obs_report.summary(evs)
            if spill:
                r["trace"]["file"] = spill
            if obs_trace.rotations():
                # The spill rotated (JEPSEN_TPU_TRACE_MAX_MB): the
                # summary covers only the live file's tail — say so,
                # in the artifact AND the perf-ledger record.
                r["trace"]["rotations"] = obs_trace.rotations()
    except Exception:  # noqa: BLE001 - observability must not cost
        pass           # the probe result
    # ONE perf-ledger record per probe run (the cross-run memory every
    # bench/probe-config5 rung feeds; ping is the recovery helper, not
    # evidence). JEPSEN_TPU_PERF_TAG names the partitioned ladder's
    # rung so each rung trends as its own row. record() never raises —
    # a ledger I/O failure cannot cost the probe result below.
    if key != "ping" and isinstance(r, dict):
        try:
            from jepsen_tpu.obs import ledger as perf_ledger

            q_new = []
            if _sup is not None:
                # Only CRASH EVIDENCE fails the perf gate — judged by
                # THE authoritative predicate (supervise.quarantined:
                # faults always, wedges only at the quarantine
                # streak, never the static gate's predictions), so
                # the gate's evidence cannot drift from what actually
                # routes.
                q_new = sorted(
                    k for k in _sup.ledger_delta(q_before)
                    if _sup.quarantined(k) is not None)
            extra = {}
            if r.get("resumed_from_row") is not None:
                # A checkpoint-resumed run's wall covers only the
                # tail: the record says so, and ledger trend/gate
                # exclude it from the wall/dispatch baselines (a
                # 300 s resumed tail must not poison the median full
                # 3217 s runs are judged against).
                extra["resumed_from_row"] = r["resumed_from_row"]
            if isinstance(r.get("pack"), dict):
                # Pack-seconds + packer mode (ISSUE 16): inert to the
                # gate rules, but `perf report`/`perf diff` trend it
                # so a packer regression shows up cross-run.
                extra["pack"] = r["pack"]
            if isinstance(r.get("mesh"), dict):
                # Per-device mesh-stats (ISSUE 18): dispatches,
                # dispatch wall, peak shard occupancy — the mesh
                # rung's before/after evidence in `perf report`.
                extra["mesh"] = r["mesh"]
            perf_ledger.record(
                os.environ.get("JEPSEN_TPU_PERF_TAG") or key,
                kind="bench", wall_s=wall_s, verdict=r.get("verdict"),
                error=r.get("error"), host_stats=r.get("host_stats"),
                trace=r.get("trace"), fleet=r.get("fleet"),
                quarantine_new=q_new, extra=extra)
        except Exception:  # noqa: BLE001 - loss-proof contract
            pass
    with lock:
        print(json.dumps(r))
        sys.stdout.flush()
    sys.exit(0)


def _ledger_headline(detail: dict, rate: float,
                     error: str | None = None) -> None:
    """One perf-ledger record for the headline check (the probe
    children record their own runs in ``_probe_main``). The crash-free
    FALLBACK run stamps its error + variant so the gate's
    error-appeared rule can see the degradation — a fallback that
    looked like a healthy headline would blind the sentinel to exactly
    the failure class it exists to catch. Never raises — obs/ledger's
    loss-proof contract."""
    try:
        from jepsen_tpu.obs import ledger as perf_ledger

        perf_ledger.record(
            "headline", kind="bench",
            wall_s=detail.get("check_seconds"),
            verdict=detail.get("verdict"),
            error=error,
            extra={"ops_per_sec": round(rate, 1),
                   "variant": detail.get("variant"),
                   "check_seconds_runs":
                       detail.get("check_seconds_runs"),
                   "dense_backend": detail.get("dense_backend")})
    except Exception:  # noqa: BLE001 - observability only
        pass


def _ledger_wide(wall_s: float, error: str | None) -> None:
    """The wide-probes sweep's health row (see the call sites in
    ``main``). Never raises — obs/ledger's loss-proof contract."""
    try:
        from jepsen_tpu.obs import ledger as perf_ledger

        perf_ledger.record("wide-probes", kind="bench",
                           wall_s=wall_s,
                           verdict=True if error is None else None,
                           error=error)
    except Exception:  # noqa: BLE001 - observability only
        pass


def main() -> None:
    from jepsen_tpu.lin import synth
    from jepsen_tpu.util import enable_compile_cache

    enable_compile_cache()

    t_start = time.time()
    target_rate = N_OPS / TARGET_SECONDS
    out = {"metric": "lin_check_ops_per_sec", "value": 0,
           "unit": "ops/s", "vs_baseline": 0}

    try:
        h = synth.generate_register_history(
            N_OPS, concurrency=5, seed=42, value_range=5,
            crash_prob=0.001, max_crashes=10)
        rate, detail = _check_timed(h, N_OPS)
        out.update(value=round(rate, 1),
                   vs_baseline=round(rate / target_rate, 3),
                   detail=detail)
        _emit(out)   # the headline survives any later timeout/fault
        _ledger_headline(detail, rate)
        try:
            t_wide = time.time()
            _wide_probes(detail, out, t_start)
            # The probe MACHINERY's own health row: recorded True on
            # every completed sweep so a later machinery crash (the
            # except below) FLIPS it — without a baseline row, a
            # bench whose probes silently stopped running would leave
            # the sentinel green (the probes' own records just
            # wouldn't exist).
            _ledger_wide(time.time() - t_wide, None)
        except Exception:
            # A probe-machinery crash must not reach the headline
            # except-branch below: the crash-free fallback there
            # REPLACES out["value"]/["detail"], so the driver's
            # last-line parse would lose the crashed-op headline and
            # every completed probe — the exact erasure the loss-proof
            # contract forbids. Keep what we have, but surface the
            # degradation at the TOP level too (the exit-code formula
            # still returns 0 while value > 0, so the headline stands
            # and the missing probes are visible without digging).
            detail["wide_probes_error"] = traceback.format_exc(limit=4)
            out["error"] = ("wide probes crashed (headline + completed "
                            "probes retained): see "
                            "detail.wide_probes_error")
            _ledger_wide(time.time() - t_wide,
                         detail["wide_probes_error"])
    except Exception:
        err = traceback.format_exc(limit=3)
        # Partial signal: the crash-free 100k history on the same engine.
        try:
            h = synth.generate_register_history(
                N_OPS, concurrency=5, seed=42, value_range=5, crash_prob=0)
            rate, detail = _check_timed(h, N_OPS)
            detail["variant"] = "crash-free fallback"
            out.update(value=round(rate, 1),
                       vs_baseline=round(rate / target_rate, 3),
                       detail=detail,
                       error=f"crashed-op run failed: {err}")
            _ledger_headline(detail, rate, error=out.get("error"))
        except Exception:
            out.update(error=f"crashed-op run failed: {err}; "
                             f"fallback failed: "
                             f"{traceback.format_exc(limit=3)}")
            # Even a total headline failure is evidence: a None
            # verdict on the headline row makes the next `perf gate`
            # flip against the last healthy record.
            _ledger_headline({}, 0.0, error=out.get("error"))

    _emit(out)
    sys.exit(0 if "error" not in out else (0 if out["value"] else 1))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--probe":
        _probe_main(sys.argv[2])
    else:
        main()
