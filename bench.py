"""Headline benchmark: linearizability checking throughput on device.

North star (BASELINE.md): decide a 100k-op CAS-register history in <60 s
where CPU knossos DNFs. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
with vs_baseline = achieved ops/s over the 100k-in-60s target rate.

Runs on whatever jax.devices() provides (the real TPU chip under the
driver). The history carries crashed ops (the frontier-inflating case that
makes CPU checkers struggle) but stays within one device's bitset window.
"""

from __future__ import annotations

import json
import sys
import time

N_OPS = 100_000
TARGET_SECONDS = 60.0


def main() -> None:
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import bfs, prepare, synth

    h = synth.generate_register_history(
        N_OPS, concurrency=5, seed=42, value_range=5,
        crash_prob=0.001, max_crashes=10)

    t0 = time.time()
    p = prepare.prepare(m.cas_register(), h)
    prep_s = time.time() - t0

    # Warm the compile cache on a small same-shaped-bucket history so the
    # measured run is the steady-state check (first TPU compile is slow).
    warm = prepare.prepare(m.cas_register(), synth.generate_register_history(
        256, concurrency=5, seed=7, crash_prob=0.01, max_crashes=4))
    bfs.check_packed(warm, cap_schedule=(1024,))

    t0 = time.time()
    result = bfs.check_packed(p, cap_schedule=(1024, 16384))
    check_s = time.time() - t0

    if result["valid?"] is not True:
        print(json.dumps({"metric": "lin_check_ops_per_sec", "value": 0,
                          "unit": "ops/s", "vs_baseline": 0,
                          "error": f"unexpected verdict {result}"}))
        sys.exit(1)

    ops_per_sec = N_OPS / check_s
    target_rate = N_OPS / TARGET_SECONDS
    print(json.dumps({
        "metric": "lin_check_ops_per_sec",
        "value": round(ops_per_sec, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_per_sec / target_rate, 3),
        "detail": {"n_ops": N_OPS, "check_seconds": round(check_s, 2),
                   "prepare_seconds": round(prep_s, 2),
                   "window": p.window, "return_events": int(p.R),
                   "verdict": result["valid?"],
                   "analyzer": result.get("analyzer")},
    }))


if __name__ == "__main__":
    main()
