"""Headline benchmark: linearizability checking throughput on device.

North star (BASELINE.md): decide a 100k-op CAS-register history in <60 s
where CPU knossos DNFs. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
with vs_baseline = achieved ops/s over the 100k-in-60s target rate.

The headline history carries crashed (:info) ops — the frontier-inflating
case that makes list-based checkers struggle — checked by the dense
config-space bitmap engine (jepsen_tpu.lin.dense), which crashed ops cost
nothing extra. Two secondary probes cover BASELINE config 5's band
(cockroach-class concurrency 30, cockroach.clj:40-41), where the sparse
engine's exact reductions + dominance pruning decide histories knossos
DNFs on outright:

- ``wide_window_c30``: a saturated single-register history at
  concurrency 30 (window ~26).
- ``partitioned_c30``: a partition-nemesis history (the literal config-5
  shape): minority ops crash indeterminate during partitions.

Runs on whatever jax.devices() provides (the real TPU chip under the
driver). Hardened: any failure on the crashed-op history still reports
the crash-free number with an "error" field instead of a bare nonzero
exit, so a round never records zero information.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

N_OPS = 100_000
TARGET_SECONDS = 60.0


def _check_timed(history, n_ops):
    """(prepare_s, warm check to compile every chunk bucket, timed check).
    Returns (ops_per_sec, detail_dict); raises on any failure."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import device_check_packed, prepare

    t0 = time.time()
    p = prepare.prepare(m.cas_register(), history)
    prep_s = time.time() - t0

    # Big chunks amortize the per-dispatch fixed costs (the bench wants
    # peak sustained throughput; the default is tuned for verdict+witness
    # latency instead). Measured on the v5e chip: 106k ops/s at 32768,
    # 118k at 65536.
    kw = {"chunk": 65536}

    # Warm run: compiles every (window-bucket, state-bucket) program this
    # history touches, so the timed runs measure steady-state throughput.
    r = device_check_packed(p, **kw)
    if r["valid?"] is not True:
        raise RuntimeError(f"unexpected verdict {r}")

    # Best of three: the shared-chip tunnel occasionally stalls a run.
    # ALL three run times are recorded (check_seconds_runs) so tunnel
    # variance is visible in the artifact — BENCH_r03's 87.7k "regression"
    # against r02's 120k was chip contention, not code (re-measured
    # 0.824 s on the same commit).
    runs = []
    for _ in range(3):
        t0 = time.time()
        r = device_check_packed(p, **kw)
        runs.append(round(time.time() - t0, 3))
        if r["valid?"] is not True:
            raise RuntimeError(f"unexpected verdict {r}")
    check_s = min(runs)

    return n_ops / check_s, {
        "n_ops": n_ops, "check_seconds": round(check_s, 3),
        "check_seconds_runs": runs,
        "prepare_seconds": round(prep_s, 2),
        # Honest end-to-end rate: host packing + device check. The
        # device-only number is the headline (prepare is amortizable:
        # it's one linear pass, reusable across re-checks), but both
        # are recorded so no claim needs the favorable denominator.
        "end_to_end_ops_per_sec": round(n_ops / (check_s + prep_s), 1),
        "window": p.window, "return_events": int(p.R),
        "verdict": r["valid?"], "analyzer": r.get("analyzer")}


def _probe(detail: dict, key: str, make_history, n_ops: int,
           model=None) -> None:
    """Run one secondary capability probe: warm once (compile), then
    time. Never fails the bench; records timing or the error."""
    import traceback

    try:
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import device_check_packed, prepare

        h = make_history()
        p = prepare.prepare(model if model is not None
                            else m.cas_register(), h)
        r = device_check_packed(p)          # warm/compile
        t0 = time.time()
        r = device_check_packed(p)
        dt = time.time() - t0
        detail[key] = {
            "n_ops": n_ops, "window": p.window,
            "crashed": len(p.crashed_ops),
            "verdict": r.get("valid?"),
            "analyzer": r.get("analyzer"),
            "seconds": round(dt, 1),
            "ops_per_sec": round(n_ops / dt, 1)}
    except Exception:
        detail[key] = {"error": traceback.format_exc(limit=2)}


def _wide_probes(detail: dict) -> None:
    """BASELINE config-5 probes (skippable via JEPSEN_TPU_BENCH_WIDE=0).
    The class where list-based searches — the reference's knossos at
    cockroach's concurrency, cockroach.clj:40-41 — DNF outright."""
    if os.environ.get("JEPSEN_TPU_BENCH_WIDE", "1") == "0":
        return
    from jepsen_tpu.lin import synth

    _probe(detail, "wide_window_c30",
           lambda: synth.generate_register_history(
               500, concurrency=30, seed=7, value_range=5,
               crash_prob=0.002, max_crashes=4), 500)
    if "error" not in detail.get("wide_window_c30", {}):
        detail["wide_window_c30"]["note"] = (
            "adversarial ceiling: fully saturated window-26 schedule, "
            "denser than the config-5 pacing partitioned_c30 measures")
    # The literal config-5 shape at the reference's staggered pacing
    # (etcd.clj:167-179 staggers invocations; invoke_bias=0.45 models
    # that): 30 processes, partition crashes, ~6-13 live ops in flight,
    # 24 crashed mutators accumulating over ~50 partition cycles
    # (window 49) — at the LITERAL 100k-op size of BASELINE config 5.
    _probe(detail, "partitioned_c30",
           lambda: synth.generate_partitioned_register_history(
               100_000, seed=7, invoke_bias=0.45), 100_000)
    # BASELINE config 3: lock (Mutex) histories at the same concurrency
    # (hazelcast.clj:379-386 / zookeeper locks). Contention serializes
    # the window, so the dense engine absorbs these.
    from jepsen_tpu import models as m

    _probe(detail, "mutex_c30",
           lambda: synth.generate_mutex_history(
               5000, concurrency=30, seed=7, crash_prob=0.002,
               max_crashes=4), 5000, model=m.mutex())


def main() -> None:
    from jepsen_tpu.lin import synth
    from jepsen_tpu.util import enable_compile_cache

    enable_compile_cache()

    target_rate = N_OPS / TARGET_SECONDS
    out = {"metric": "lin_check_ops_per_sec", "value": 0,
           "unit": "ops/s", "vs_baseline": 0}

    try:
        h = synth.generate_register_history(
            N_OPS, concurrency=5, seed=42, value_range=5,
            crash_prob=0.001, max_crashes=10)
        rate, detail = _check_timed(h, N_OPS)
        out.update(value=round(rate, 1),
                   vs_baseline=round(rate / target_rate, 3),
                   detail=detail)
        _wide_probes(detail)
    except Exception:
        err = traceback.format_exc(limit=3)
        # Partial signal: the crash-free 100k history on the same engine.
        try:
            h = synth.generate_register_history(
                N_OPS, concurrency=5, seed=42, value_range=5, crash_prob=0)
            rate, detail = _check_timed(h, N_OPS)
            detail["variant"] = "crash-free fallback"
            out.update(value=round(rate, 1),
                       vs_baseline=round(rate / target_rate, 3),
                       detail=detail,
                       error=f"crashed-op run failed: {err}")
        except Exception:
            out.update(error=f"crashed-op run failed: {err}; "
                             f"fallback failed: "
                             f"{traceback.format_exc(limit=3)}")

    print(json.dumps(out))
    sys.stdout.flush()
    sys.exit(0 if "error" not in out else (0 if out["value"] else 1))


if __name__ == "__main__":
    main()
