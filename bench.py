"""Headline benchmark: linearizability checking throughput on device.

North star (BASELINE.md): decide a 100k-op CAS-register history in <60 s
where CPU knossos DNFs. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
with vs_baseline = achieved ops/s over the 100k-in-60s target rate.

The headline history carries crashed (:info) ops — the frontier-inflating
case that makes list-based checkers struggle — checked by the dense
config-space bitmap engine (jepsen_tpu.lin.dense), which crashed ops cost
nothing extra. Secondary probes cover BASELINE configs 3-5:

- ``mutex_c30``: lock histories at concurrency 30 (config 3).
- ``wide_window_c30``: a saturated single-register history at
  concurrency 30 (window ~26) — the class knossos DNFs on.
- ``independent_keys``: 1k keys' subhistories decided in one vmapped
  device batch (config 4, independent.clj:246-296).
- ``partitioned_c30``: the literal config-5 shape — a 100k-op
  partition-nemesis history, 24 crashed mutators, window 49.

FAULT ISOLATION: every secondary probe runs in its own subprocess
(``python bench.py --probe KEY``), so a TPU worker crash kills the
child, not the bench — round 4 lost a known-good probe to the previous
probe's kernel fault. Probes run safe-first; after a failed probe the
bench waits out the ~60 s worker restart and verifies recovery with a
trivial dispatch before the next probe.

Runs on whatever jax.devices() provides (the real TPU chip under the
driver).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback

N_OPS = 100_000
TARGET_SECONDS = 60.0

# (key, timeout_seconds) safe-first: the long/dangerous partitioned
# probe runs LAST so it cannot shadow any other number. Its budget is
# wide: the 100k partitioned check runs ~tens of minutes through the
# host-row executor's wave segments (decided at all is the round-5
# breakthrough; it was a kernel fault before).
PROBE_ORDER = (("mutex_c30", 600), ("wide_window_c30", 600),
               ("independent_keys", 900), ("partitioned_c30", 5300))
WORKER_RESTART_S = 75


def _check_timed(history, n_ops):
    """(prepare_s, warm check to compile every chunk bucket, timed check).
    Returns (ops_per_sec, detail_dict); raises on any failure."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import device_check_packed, prepare

    t0 = time.time()
    p = prepare.prepare(m.cas_register(), history)
    prep_s = time.time() - t0

    # Big chunks amortize the per-dispatch fixed costs (the bench wants
    # peak sustained throughput; the default is tuned for verdict+witness
    # latency instead). Measured on the v5e chip: 106k ops/s at 32768,
    # 118k at 65536.
    kw = {"chunk": 65536}

    # Warm run: compiles every (window-bucket, state-bucket) program this
    # history touches, so the timed runs measure steady-state throughput.
    r = device_check_packed(p, **kw)
    if r["valid?"] is not True:
        raise RuntimeError(f"unexpected verdict {r}")

    # Best of three: the shared-chip tunnel occasionally stalls a run.
    # ALL three run times are recorded (check_seconds_runs) so tunnel
    # variance is visible in the artifact — BENCH_r03's 87.7k "regression"
    # against r02's 120k was chip contention, not code (re-measured
    # 0.824 s on the same commit).
    runs = []
    for _ in range(3):
        t0 = time.time()
        r = device_check_packed(p, **kw)
        runs.append(round(time.time() - t0, 3))
        if r["valid?"] is not True:
            raise RuntimeError(f"unexpected verdict {r}")
    check_s = min(runs)

    return n_ops / check_s, {
        "n_ops": n_ops, "check_seconds": round(check_s, 3),
        "check_seconds_runs": runs,
        "prepare_seconds": round(prep_s, 2),
        # Honest end-to-end rate: host packing + device check. The
        # device-only number is the headline (prepare is amortizable:
        # it's one linear pass, reusable across re-checks), but both
        # are recorded so no claim needs the favorable denominator.
        "end_to_end_ops_per_sec": round(n_ops / (check_s + prep_s), 1),
        "window": p.window, "return_events": int(p.R),
        "verdict": r["valid?"], "analyzer": r.get("analyzer"),
        # Which dense chunk backend decided (VERDICT r4 #4): "pallas"
        # is the in-VMEM whole-frontier kernel, auto-routed on TPU
        # since round 4 (dense.py backend="auto").
        "dense_backend": r.get("backend")}


def _timed_check(make_history, n_ops, model=None, warm=True):
    """Warm once (compile), then time one device check. ``warm=False``
    times the first run instead (long probes: the persistent compile
    cache already amortizes compiles, and a second multi-minute run
    would blow the probe budget for no extra information). Returns the
    probe's result dict."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import device_check_packed, prepare

    h = make_history()
    p = prepare.prepare(model if model is not None
                        else m.cas_register(), h)
    if warm:
        r = device_check_packed(p)      # warm/compile
    t0 = time.time()
    r = device_check_packed(p)
    dt = time.time() - t0
    return {
        "n_ops": n_ops, "window": p.window,
        "crashed": len(p.crashed_ops),
        "verdict": r.get("valid?"),
        "analyzer": r.get("analyzer"),
        "timed_run": "steady" if warm else "first",
        "seconds": round(dt, 1),
        "ops_per_sec": round(n_ops / dt, 1)}


def _probe_ping():
    """Trivial device dispatch: proves the TPU worker is back up."""
    import jax
    import jax.numpy as jnp

    x = int(jnp.sum(jnp.arange(8)))
    return {"ok": x == 28, "platform": jax.devices()[0].platform}


def _probe_mutex_c30():
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import synth

    return _timed_check(
        lambda: synth.generate_mutex_history(
            5000, concurrency=30, seed=7, crash_prob=0.002,
            max_crashes=4), 5000, model=m.mutex())


def _probe_wide_window_c30():
    from jepsen_tpu.lin import synth

    r = _timed_check(
        lambda: synth.generate_register_history(
            500, concurrency=30, seed=7, value_range=5,
            crash_prob=0.002, max_crashes=4), 500)
    r["note"] = ("adversarial ceiling: fully saturated window-26 "
                 "schedule, denser than the config-5 pacing "
                 "partitioned_c30 measures")
    return r


def _probe_partitioned_c30():
    # The literal config-5 shape at the reference's staggered pacing
    # (etcd.clj:167-179 staggers invocations; invoke_bias=0.45 models
    # that): 30 processes, partition crashes, ~6-13 live ops in flight,
    # 24 crashed mutators accumulating over ~50 partition cycles
    # (window 49) — at the LITERAL 100k-op size of BASELINE config 5.
    from jepsen_tpu.lin import synth

    return _timed_check(
        lambda: synth.generate_partitioned_register_history(
            100_000, seed=7, invoke_bias=0.45), 100_000, warm=False)


def _probe_independent_keys():
    """BASELINE config 4: per-key registers decided as ONE vmapped
    device batch (lin.batched; independent.clj:246-296 checks keys one
    at a time on the JVM)."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import batched, synth

    n_keys, ops_per_key = 1000, 100
    subs = {k: synth.generate_register_history(
        ops_per_key, concurrency=5, seed=1000 + k, value_range=5,
        crash_prob=0.002, max_crashes=2) for k in range(n_keys)}
    model = m.cas_register()
    r = batched.try_check_batch(model, subs)    # warm/compile
    if r is None or len(r) < n_keys:
        raise RuntimeError(
            f"batch covered {0 if r is None else len(r)}/{n_keys} keys")
    t0 = time.time()
    r = batched.try_check_batch(model, subs)
    dt = time.time() - t0
    false_keys = sum(1 for v in r.values() if v["valid?"] is False)
    unknown_keys = sum(1 for v in r.values()
                       if v["valid?"] not in (True, False))
    n_ops = n_keys * ops_per_key
    return {"n_ops": n_ops, "n_keys": n_keys,
            # All histories are linearizable by construction: any False
            # is a checker bug, any non-bool an undecided key.
            "verdict": True if not (false_keys or unknown_keys)
            else ("unknown" if not false_keys else False),
            "false_keys": false_keys, "unknown_keys": unknown_keys,
            "analyzer": next(iter(r.values()))["analyzer"],
            "seconds": round(dt, 2),
            "ops_per_sec": round(n_ops / dt, 1)}


PROBES = {"ping": _probe_ping, "mutex_c30": _probe_mutex_c30,
          "wide_window_c30": _probe_wide_window_c30,
          "partitioned_c30": _probe_partitioned_c30,
          "independent_keys": _probe_independent_keys}


def _run_probe_subprocess(key: str, timeout: int):
    """Run one probe isolated in a child process; returns its result
    dict or {"error": ...}. The child prints ONE json line on its last
    stdout line."""
    try:
        cp = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe", key],
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"error": f"probe timed out after {timeout}s"}
    lines = [ln for ln in (cp.stdout or "").splitlines() if ln.strip()]
    if lines:
        try:
            return json.loads(lines[-1])
        except json.JSONDecodeError:
            pass
    tail = ((cp.stderr or "") + (cp.stdout or ""))[-2000:]
    return {"error": f"probe exited rc={cp.returncode}: {tail}"}


def _verify_recovery() -> bool:
    """After a probe failure (possible worker crash), wait out the
    restart and prove the chip answers again."""
    for _ in range(3):
        time.sleep(WORKER_RESTART_S)
        r = _run_probe_subprocess("ping", 120)
        if r.get("ok"):
            return True
    return False


def _wide_probes(detail: dict) -> None:
    """BASELINE config 3-5 probes (skippable via JEPSEN_TPU_BENCH_WIDE=0),
    each in its own subprocess, safe-first (see module docstring)."""
    if os.environ.get("JEPSEN_TPU_BENCH_WIDE", "1") == "0":
        return
    for key, timeout in PROBE_ORDER:
        r = _run_probe_subprocess(key, timeout)
        detail[key] = r
        if "error" in r:
            # The fault may have killed the worker; recover before the
            # next probe so one crash cannot shadow later numbers.
            recovered = _verify_recovery()
            detail[key]["worker_recovered"] = recovered
            if not recovered:
                break


def _probe_main(key: str) -> None:
    from jepsen_tpu.util import enable_compile_cache

    enable_compile_cache()
    try:
        r = PROBES[key]()
    except Exception:
        r = {"error": traceback.format_exc(limit=4)}
    print(json.dumps(r))
    sys.stdout.flush()
    sys.exit(0)


def main() -> None:
    from jepsen_tpu.lin import synth
    from jepsen_tpu.util import enable_compile_cache

    enable_compile_cache()

    target_rate = N_OPS / TARGET_SECONDS
    out = {"metric": "lin_check_ops_per_sec", "value": 0,
           "unit": "ops/s", "vs_baseline": 0}

    try:
        h = synth.generate_register_history(
            N_OPS, concurrency=5, seed=42, value_range=5,
            crash_prob=0.001, max_crashes=10)
        rate, detail = _check_timed(h, N_OPS)
        out.update(value=round(rate, 1),
                   vs_baseline=round(rate / target_rate, 3),
                   detail=detail)
        _wide_probes(detail)
    except Exception:
        err = traceback.format_exc(limit=3)
        # Partial signal: the crash-free 100k history on the same engine.
        try:
            h = synth.generate_register_history(
                N_OPS, concurrency=5, seed=42, value_range=5, crash_prob=0)
            rate, detail = _check_timed(h, N_OPS)
            detail["variant"] = "crash-free fallback"
            out.update(value=round(rate, 1),
                       vs_baseline=round(rate / target_rate, 3),
                       detail=detail,
                       error=f"crashed-op run failed: {err}")
        except Exception:
            out.update(error=f"crashed-op run failed: {err}; "
                             f"fallback failed: "
                             f"{traceback.format_exc(limit=3)}")

    print(json.dumps(out))
    sys.stdout.flush()
    sys.exit(0 if "error" not in out else (0 if out["value"] else 1))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--probe":
        _probe_main(sys.argv[2])
    else:
        main()
