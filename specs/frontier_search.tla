---- MODULE frontier_search ----
(***************************************************************************)
(* The linearizability frontier search (jepsen_tpu/lin) as a TLA+ spec.   *)
(*                                                                        *)
(* The device kernel (lin/bfs.py) walks a history's return events         *)
(* maintaining a frontier of (linearized-set x model-state) configs:      *)
(*   closure:  linearize any pending op legal in some config              *)
(*   filter:   keep configs that linearized the returning op              *)
(*   recycle:  clear the returner's bit                                   *)
(* and reports valid iff the frontier never empties.  This module states  *)
(* the correctness of that loop for a CAS register: the frontier search   *)
(* is non-empty at every step iff some witness linearization exists       *)
(* (Soundness below); TLC checks it over all small histories the model    *)
(* generator produces.                                                    *)
(*                                                                        *)
(* Suggested TLC config:                                                  *)
(*   Procs = {p1, p2}  Vals = {1, 2}  MaxOps = 3                          *)
(*   INVARIANT TypeOK, Soundness                                          *)
(***************************************************************************)

EXTENDS Naturals, Sequences, FiniteSets, TLC

CONSTANTS Procs, Vals, MaxOps

VARIABLES
    hist,     \* sequence of records [p, f, arg, done]
    pending,  \* procs with an open invocation: proc -> index into hist
    frontier  \* set of [lin: SUBSET indices, st: register value]

vars == <<hist, pending, frontier>>

Nil == 0    \* register starts empty; Vals must not contain 0

Ops == [f : {"read", "write", "cas"},
        arg : (Vals \cup {Nil}) \X (Vals \cup {Nil})]

---------------------------------------------------------------------------
(* Model step for the CAS register (models/kernels.py semantics):
   read checks the observed value, write always applies, cas applies iff
   the current value matches. *)
Step(st, f, arg) ==
    CASE f = "read"  -> IF arg[1] \in {Nil, st}
                        THEN {st} ELSE {}
      [] f = "write" -> {arg[1]}
      [] f = "cas"   -> IF st = arg[1] THEN {arg[2]} ELSE {}

---------------------------------------------------------------------------
(* Frontier transforms — the executable content of lin/bfs.py *)

\* One closure pass: every config may additionally linearize any pending
\* op whose step is legal from its state.
Expand(F) ==
    F \cup { [lin |-> c.lin \cup {i}, st |-> s] :
             c \in F,
             i \in {j \in DOMAIN hist :
                        /\ hist[j].open
                        /\ j \notin c.lin},
             s \in Step(c.st, hist[i].f, hist[i].arg) }

RECURSIVE Closure(_)
Closure(F) == LET F2 == Expand(F) IN IF F2 = F THEN F ELSE Closure(F2)

Filter(F, i) == { c \in F : i \in c.lin }

---------------------------------------------------------------------------
Init ==
    /\ hist = <<>>
    /\ pending = [p \in Procs |-> 0]
    /\ frontier = {[lin |-> {}, st |-> Nil]}

Invoke(p, op) ==
    /\ pending[p] = 0
    /\ Len(hist) < MaxOps
    /\ hist' = Append(hist, [p |-> p, f |-> op.f, arg |-> op.arg,
                             open |-> TRUE, done |-> FALSE])
    /\ pending' = [pending EXCEPT ![p] = Len(hist')]
    /\ frontier' = frontier
\* an invocation only widens what Closure may linearize

Return(p) ==
    /\ pending[p] /= 0
    /\ LET i == pending[p] IN
        /\ hist' = [hist EXCEPT ![i].done = TRUE, ![i].open = FALSE]
        /\ frontier' = { [lin |-> c.lin \ {i}, st |-> c.st] :
                         c \in Filter(Closure(frontier), i) }
        \* recycle: in lin/bfs.py the slot bit clears; here we drop the
        \* index from lin, the same quotient.
    /\ pending' = [pending EXCEPT ![p] = 0]

Next == \E p \in Procs :
            \/ \E op \in Ops : Invoke(p, op)
            \/ Return(p)

Spec == Init /\ [][Next]_vars

---------------------------------------------------------------------------
TypeOK ==
    /\ pending \in [Procs -> 0..MaxOps]
    /\ \A c \in frontier : c.st \in Vals \cup {Nil}

(* Soundness: the frontier is exactly the reachable set of the abstract
   search — it is empty only when no linearization of the completed ops
   exists.  We state the checkable direction: every frontier config's
   state is producible by SOME sequential application of a subset of
   issued ops, i.e. the search never invents states. *)
RECURSIVE Reachable(_, _)
Reachable(st, linset) ==
    IF linset = {} THEN st = Nil
    ELSE \E i \in linset :
            \E prev \in Vals \cup {Nil} :
                /\ st \in Step(prev, hist[i].f, hist[i].arg)
                /\ Reachable(prev, linset \ {i})

Soundness == \A c \in frontier : Reachable(c.st, c.lin)

====
