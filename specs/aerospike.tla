---- MODULE aerospike ----
(***************************************************************************)
(* A model of Aerospike-style cluster-view formation and replicated       *)
(* register writes under network partitions.  Formal-artifact parity with *)
(* the reference's aerospike/spec/aerospike.tla (its only TLA+ spec);     *)
(* this is an independent formulation.                                    *)
(*                                                                        *)
(* The point of the model: an AP design in which every connected          *)
(* component forms its own cluster view and keeps accepting writes lets   *)
(* TLC find the divergent-commit anomaly that the jepsen_tpu aerospike    *)
(* suite observes empirically (suites/aerospike.py) — two partitions each *)
(* committing conflicting values for one key.  Checking Divergence as an  *)
(* invariant yields the counterexample trace; QuorumWritesConverge holds  *)
(* when writes additionally require a majority component.                 *)
(*                                                                        *)
(* Model-check with TLC, e.g.:                                           *)
(*   Roster = {a1, a2, a3}   ReplicationFactor = 2                        *)
(*   INVARIANT TypeOK, QuorumSafe                                         *)
(***************************************************************************)

EXTENDS Naturals, FiniteSets, TLC

CONSTANTS
    Roster,              \* set of server nodes
    ReplicationFactor,   \* copies a write needs before ack
    Values               \* values clients may write

ASSUME ReplicationFactor \in 1..Cardinality(Roster)

VARIABLES
    links,    \* symmetric connectivity: set of {m, n} pairs currently up
    view,     \* view[n]: the set of nodes n currently believes alive
    store,    \* store[n]: the value node n holds for the single key
    committed \* set of <<component, value>> write acks handed to clients

vars == <<links, view, store, committed>>

None == CHOOSE x : x \notin Values

---------------------------------------------------------------------------
(* Connectivity helpers *)

Connected(m, n) == m = n \/ {m, n} \in links

\* The connected component of n under the current links (transitive closure
\* via a fixpoint over subsets).
Component(n) ==
    LET grow[S \in SUBSET Roster] ==
        LET next == S \cup {m \in Roster : \E s \in S : Connected(s, m)}
        IN IF next = S THEN S ELSE grow[next]
    IN grow[{n}]

Majority(S) == 2 * Cardinality(S) > Cardinality(Roster)

---------------------------------------------------------------------------
(* Initial state: fully connected, empty register *)

Init ==
    /\ links = {{m, n} : m \in Roster, n \in Roster \ {m}}
    /\ view = [n \in Roster |-> Roster]
    /\ store = [n \in Roster |-> None]
    /\ committed = {}

---------------------------------------------------------------------------
(* Transitions *)

\* The nemesis cuts or heals one link.
Cut(m, n) ==
    /\ m /= n /\ {m, n} \in links
    /\ links' = links \ {{m, n}}
    /\ UNCHANGED <<view, store, committed>>

Heal(m, n) ==
    /\ m /= n /\ {m, n} \notin links
    /\ links' = links \cup {{m, n}}
    /\ UNCHANGED <<view, store, committed>>

\* Heartbeat exchange: n adopts its connected component as its view.
\* (Aerospike forms the view from heartbeat adjacency; we abstract the
\* gossip rounds into one step.)
Observe(n) ==
    /\ view' = [view EXCEPT ![n] = Component(n)]
    /\ UNCHANGED <<links, store, committed>>

\* An AP write: coordinator n accepts a write when its *view* contains at
\* least ReplicationFactor nodes, replicates to the reachable replicas,
\* and acks.  No majority requirement — this is the unsafe behavior.
WriteAP(n, v) ==
    /\ Cardinality(view[n]) >= ReplicationFactor
    /\ LET reach == Component(n) IN
        /\ store' = [m \in Roster |->
                        IF m \in reach THEN v ELSE store[m]]
        /\ committed' = committed \cup {<<reach, v>>}
    /\ UNCHANGED <<links, view>>

\* A CP-flavored write: additionally requires the coordinator's component
\* to be a majority of the roster.
WriteQuorum(n, v) ==
    /\ Majority(Component(n))
    /\ WriteAP(n, v)

Next ==
    \/ \E m \in Roster, n \in Roster : Cut(m, n) \/ Heal(m, n)
    \/ \E n \in Roster : Observe(n)
    \/ \E n \in Roster, v \in Values : WriteAP(n, v)

Spec == Init /\ [][Next]_vars

---------------------------------------------------------------------------
(* Properties *)

TypeOK ==
    /\ links \subseteq {{m, n} : m \in Roster, n \in Roster \ {m}}
    /\ view \in [Roster -> SUBSET Roster]
    /\ store \in [Roster -> Values \cup {None}]

\* Two disjoint components have both acked writes: split-brain commits.
\* Under WriteAP with ReplicationFactor < majority, TLC refutes this —
\* reproducing the data-loss anomaly the harness finds on real clusters.
Divergence ==
    \E c1 \in committed, c2 \in committed :
        /\ c1[1] \cap c2[1] = {}
        /\ c1[2] /= c2[2]

QuorumSafe == ~Divergence

\* With WriteQuorum substituted into Next, any two commit components
\* intersect (two majorities always share a node), so QuorumSafe holds.
QuorumWritesConverge ==
    \A c1 \in committed, c2 \in committed : c1[1] \cap c2[1] /= {}

====
