# jepsen_tpu development targets.

.PHONY: test test-quick integration integration-local bench \
	probe-config5 serve-smoke txn-smoke trace-smoke stream-smoke \
	fleet-smoke perf-smoke pack-smoke mesh-smoke lint

# Unit + parity suite on the virtual 8-device CPU mesh (no cluster).
# Hardware note: ~8 min on a 4-core box; the compile-heavy lin parity
# tests make a 1-core box take well over an hour (use test-quick there).
# Tier-1: slow-marked acceptance-scale runs (the 100k-op txn twin) are
# excluded here and from test-quick; run them with -m slow.
test:
	python -m pytest tests/ -q -m "not slow"

# Fast tier: the no-XLA-compile tests (history/generator/nemesis math,
# wire-protocol fakes, suite maps, checkers on hand histories) — about
# a minute even on one core (cold .jax_cache: a few minutes while the
# `compiles`-marked engine tests warm it). The timeout guard keeps a
# wedged process from holding the shell, and the conftest no-compile
# check (tests/conftest.py) fails any quick test that triggers an
# unexempted XLA compile — the tier's promise, enforced.
TEST_QUICK_TIMEOUT ?= 900
test-quick:
	timeout -k 15 $(TEST_QUICK_TIMEOUT) \
		python -m pytest tests/ -q -m "quick and not slow"

# Repo contract linter (doc/analysis.md): the CLAUDE.md invariants as
# a zero-findings gate — lax.while_loop iteration ceilings in
# lin/+txn/, JEPSEN_TPU_* <-> doc/env.md drift both ways, the wire
# suites' :info-never-:fail exception rule, Pallas module-constant
# hygiene, quick-tier compiles markers. Pure AST: chip-free,
# sub-second; run it before committing engine changes (CLAUDE.md).
# Exit 1 on findings; every waiver is greppable (`grep -rn 'lint:'`).
LINT_TIMEOUT ?= 120
lint:
	timeout -k 10 $(LINT_TIMEOUT) python -m jepsen_tpu.cli lint

# Cluster integration matrix against the dockerized 1-control + 5-node
# environment: brings the compose cluster up, then runs the per-suite
# register matrix (tests/test_integration_matrix.py — the analogue of
# the reference's deftest grids, cockroach_test.clj:17-52) from the
# control container. Requires docker compose on the host.
integration:
	cd docker && ./up.sh --daemon
	docker exec -e JEPSEN_NODES=n1,n2,n3,n4,n5 jepsen-tpu-control \
		python -m pytest /jepsen_tpu/tests/test_integration_matrix.py -v; \
	rc=$$?; cd docker && docker compose down; exit $$rc

# Same matrix against nodes you already have (set JEPSEN_NODES).
integration-local:
	python -m pytest tests/test_integration_matrix.py -v

# Headline benchmark on the real TPU chip (exclusive).
bench:
	python bench.py

# One-command probe of the EXACT config-5 history (100k-op partitioned,
# window 49, 24 crashed mutators, pair keys) — CLAUDE.md says to probe
# this shape after every engine change; the 5k/window-25 shapes do not
# exercise the crash-dom/host-row paths at all. Runs one timed check in
# the bench's probe harness (heartbeat lines + host-stats in the result
# JSON), timeout-guarded so a wedged tunnel dispatch cannot hold the
# shell. Takes the real TPU chip exclusively; engine env knobs
# (doc/env.md) pass through, e.g.:
#   make probe-config5 JEPSEN_TPU_HOST_ROWS_K=1
# After the run the quarantine-ledger DELTA is printed (cli.py
# quarantine diff), so an engine change that newly faults a shape is
# visible in this one command; the probe's exit code is preserved.
# Checker-daemon smoke (doc/service.md): start an in-process daemon on
# the forced-CPU mesh, submit 3 histories over a real socket, assert
# verdicts vs the CPU oracle, clean shutdown. Part of the quick-tier
# habit next to probe-config5: run it after touching the service, the
# wire layer, or lin/batched. Timeout-guarded (cold .jax_cache compiles
# a few small programs; warm runs take seconds) and chip-free, so it
# composes with anything.
SERVE_SMOKE_TIMEOUT ?= 600
serve-smoke:
	timeout -k 15 $(SERVE_SMOKE_TIMEOUT) \
		python -m jepsen_tpu.service.smoke

# Txn-checker smoke (doc/txn.md): chip-free generate -> pack -> check
# -> classify round trip on the forced CPU mesh — a healthy concurrent
# list-append history decides valid on device, and every seeded
# anomaly corpus (G0/G1c/G-single/G2-item/G1a) is found and classified
# identically by the device engine and the CPU oracle. Run it after
# touching jepsen_tpu/txn/, the txn workloads, or the checker wiring.
TXN_SMOKE_TIMEOUT ?= 600
txn-smoke:
	timeout -k 15 $(TXN_SMOKE_TIMEOUT) \
		python -m jepsen_tpu.txn.smoke

# Streaming-checker smoke (doc/streaming.md): chip-free CPU-mesh
# open -> append xN -> finalize round trip, in-process AND over the
# wire (daemon stream session), with verdict parity vs the CPU oracle
# and the corrupted twin proving mid-feed early abort. Run it after
# touching jepsen_tpu/stream/, the wire layer, core.py's live-checker
# hook, or the bfs incremental entry (frontier=/partial=).
STREAM_SMOKE_TIMEOUT ?= 600
stream-smoke:
	timeout -k 15 $(STREAM_SMOKE_TIMEOUT) \
		python -m jepsen_tpu.stream.smoke

# Fleet smoke (doc/service.md § Fleet): the checker daemon tested like
# a database — (1) an in-process chaos run (seeded wedge + fault +
# worker-kill schedule under concurrent clients, soundness audited
# against the CPU oracle: verdicts match or degrade to honest unknown,
# never flip/duplicate/vanish), then (2) a REAL SIGKILL of a daemon
# subprocess with journaled requests and an open stream session in
# flight, restart on the same journal, replay-and-re-decide parity,
# and stream-session re-adoption off its per-sid checkpoint. Chip-free
# (forced CPU mesh in both legs); artifacts under
# .jax_cache/fleet_smoke/. Run it after touching jepsen_tpu/service/,
# the journal, or the worker pool.
FLEET_SMOKE_TIMEOUT ?= 900
fleet-smoke:
	timeout -k 15 $(FLEET_SMOKE_TIMEOUT) \
		python -m jepsen_tpu.service.chaos

# Flight-recorder smoke (doc/observability.md): chip-free CPU-mesh
# check of a small sparse-engine history with JEPSEN_TPU_TRACE=1 —
# asserts the attribution report renders, the Chrome export is valid
# trace-event JSON, the /run telemetry page renders from the registry
# snapshot, and the traced verdict equals the CPU oracle. Run it after
# touching jepsen_tpu/obs/ or any span call site (supervise, the bfs
# executors, the service, txn). Artifacts land in .jax_cache/ so
# `python -m jepsen_tpu.cli trace report --file
# .jax_cache/trace_smoke.trace.jsonl` works immediately after.
TRACE_SMOKE_TIMEOUT ?= 600
trace-smoke:
	timeout -k 15 $(TRACE_SMOKE_TIMEOUT) \
		python -m jepsen_tpu.obs.smoke

# Perf-ledger smoke (doc/observability.md § Perf ledger): chip-free
# record -> report -> gate round trip on a CPU-mesh check — a real
# check recorded with git sha + env fingerprint, `perf report` renders
# its trend row, `perf gate` passes the healthy history AND catches a
# seeded injected regression (wall-time and verdict-flip cases both
# demonstrated, against a throwaway ledger so fabricated evidence
# never pollutes the real trajectory). Run it after touching
# jepsen_tpu/obs/ledger.py, the bench's ledger recording, or the gate
# rules.
PERF_SMOKE_TIMEOUT ?= 600
perf-smoke:
	timeout -k 15 $(PERF_SMOKE_TIMEOUT) \
		python -m jepsen_tpu.obs.perf_smoke

# Packer smoke (ISSUE 16): chip-free proof that the vectorized packer
# (JEPSEN_TPU_FAST_PACK=1, the default) is BIT-IDENTICAL to the Python
# spec walk (history fingerprint + slot_op) on the partitioned and
# mutex families, actually faster (soft >=1.5x gate at the smoke's
# mid-size; bench's `pack` micro-rung holds the 100k-op >=5x
# evidence), and that the pack meter's fields ride the perf-ledger
# record schema. Run it after touching lin/prepare.py, txn/pack.py,
# stream/incr.py, or the packer env knobs.
PACK_SMOKE_TIMEOUT ?= 600
pack-smoke:
	timeout -k 15 $(PACK_SMOKE_TIMEOUT) \
		python -m jepsen_tpu.lin.pack_smoke

# Crash-dom mesh smoke (ISSUE 18, doc/sharding.md): chip-free proof on
# the forced 8-device virtual CPU mesh that the sharded compact band
# decides a crash-dom history with oracle parity (valid + corrupted
# twin, same violating op, per-device mesh-stats on both verdicts) and
# that a JEPSEN_TPU_WEDGE=mesh-chunk injected run returns an honest
# `overflow: wedge` unknown. Appends its own perf-ledger record (mesh
# sub-dict). Run it after touching lin/sharded.py, the collective
# dedup, supervise's mesh-chunk site, or the JEPSEN_TPU_MESH_* knobs.
MESH_SMOKE_TIMEOUT ?= 600
mesh-smoke:
	timeout -k 15 $(MESH_SMOKE_TIMEOUT) \
		python -m jepsen_tpu.lin.mesh_smoke

PROBE_CONFIG5_TIMEOUT ?= 5400
# Frontier checkpoint: a probe killed by the timeout (or a fault)
# leaves .jax_cache/probe_config5.ckpt.npz, and the NEXT probe-config5
# run resumes the decide mid-history (resumed_from_row in its JSON)
# instead of restarting from op 0.
PROBE_CONFIG5_CKPT ?= .jax_cache/probe_config5.ckpt.npz
# Flight recorder: the probe runs traced, spilling the span timeline
# next to the checkpoint — `cli.py trace report --file
# $(PROBE_CONFIG5_TRACE)` prints where the seconds went (per-site x
# per-cap dispatch wall, compile, wasted rungs) and the trace summary
# rides in the probe JSON (doc/observability.md).
PROBE_CONFIG5_TRACE ?= .jax_cache/probe_config5.trace.jsonl
# After the run BOTH evidence deltas print: the quarantine-ledger
# delta (newly faulting shapes) and the perf-ledger delta (the probe's
# new record vs its trailing median — cli.py perf diff, the
# cross-run memory of doc/observability.md § Perf ledger).
probe-config5:
	@mkdir -p .jax_cache
	@cp "$${JEPSEN_TPU_QUARANTINE:-.jax_cache/quarantine.json}" \
		/tmp/jepsen_tpu_q5_before.json \
		2>/dev/null || echo '{"shapes": {}}' \
		> /tmp/jepsen_tpu_q5_before.json
	@cp "$${JEPSEN_TPU_PERF_LEDGER:-.jax_cache/perf_ledger.jsonl}" \
		/tmp/jepsen_tpu_p5_before.jsonl \
		2>/dev/null || : > /tmp/jepsen_tpu_p5_before.jsonl
	timeout -k 30 $(PROBE_CONFIG5_TIMEOUT) \
		env JEPSEN_TPU_CKPT=$(PROBE_CONFIG5_CKPT) \
		JEPSEN_TPU_TRACE=1 \
		JEPSEN_TPU_TRACE_FILE=$(PROBE_CONFIG5_TRACE) \
		JEPSEN_TPU_PERF_TAG=probe-config5 \
		python bench.py --probe partitioned_c30; rc=$$?; \
	python -m jepsen_tpu.cli quarantine diff \
		--before /tmp/jepsen_tpu_q5_before.json; \
	python -m jepsen_tpu.cli perf diff \
		--before /tmp/jepsen_tpu_p5_before.jsonl; exit $$rc
