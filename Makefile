# jepsen_tpu development targets.

.PHONY: test test-quick integration integration-local bench probe-config5

# Unit + parity suite on the virtual 8-device CPU mesh (no cluster).
# Hardware note: ~8 min on a 4-core box; the compile-heavy lin parity
# tests make a 1-core box take well over an hour (use test-quick there).
test:
	python -m pytest tests/ -q

# Fast tier: the no-XLA-compile tests (history/generator/nemesis math,
# wire-protocol fakes, suite maps, checkers on hand histories) — about
# a minute even on one core.
test-quick:
	python -m pytest tests/ -q -m quick

# Cluster integration matrix against the dockerized 1-control + 5-node
# environment: brings the compose cluster up, then runs the per-suite
# register matrix (tests/test_integration_matrix.py — the analogue of
# the reference's deftest grids, cockroach_test.clj:17-52) from the
# control container. Requires docker compose on the host.
integration:
	cd docker && ./up.sh --daemon
	docker exec -e JEPSEN_NODES=n1,n2,n3,n4,n5 jepsen-tpu-control \
		python -m pytest /jepsen_tpu/tests/test_integration_matrix.py -v; \
	rc=$$?; cd docker && docker compose down; exit $$rc

# Same matrix against nodes you already have (set JEPSEN_NODES).
integration-local:
	python -m pytest tests/test_integration_matrix.py -v

# Headline benchmark on the real TPU chip (exclusive).
bench:
	python bench.py

# One-command probe of the EXACT config-5 history (100k-op partitioned,
# window 49, 24 crashed mutators, pair keys) — CLAUDE.md says to probe
# this shape after every engine change; the 5k/window-25 shapes do not
# exercise the crash-dom/host-row paths at all. Runs one timed check in
# the bench's probe harness (heartbeat lines + host-stats in the result
# JSON), timeout-guarded so a wedged tunnel dispatch cannot hold the
# shell. Takes the real TPU chip exclusively; engine env knobs
# (doc/env.md) pass through, e.g.:
#   make probe-config5 JEPSEN_TPU_HOST_ROWS_K=1
PROBE_CONFIG5_TIMEOUT ?= 5400
probe-config5:
	timeout -k 30 $(PROBE_CONFIG5_TIMEOUT) \
		python bench.py --probe partitioned_c30
